"""Algorithm-specific semantics on the exact backend, under virtual time.

Covers the reference's integration scenarios (SURVEY.md §4.1 row 11) —
window boundaries, refill, burst, weighting — deterministically via
ManualClock instead of miniredis FastForward + real sleeps.
"""


import pytest

from ratelimiter_tpu import Algorithm, Config, ManualClock, create_limiter


def make(algo, limit=100, window=60.0, start=1_700_000_000.0, **kw):
    clock = ManualClock(start)
    lim = create_limiter(Config(algorithm=algo, limit=limit, window=window, **kw),
                         backend="exact", clock=clock)
    return lim, clock


# --------------------------------------------------------------- fixed window

class TestFixedWindow:
    def test_window_rolls(self):
        # Window boundary clears the count (fixedwindow_integration_test.go:173-180)
        lim, clock = make(Algorithm.FIXED_WINDOW, limit=2, window=10.0, start=1000.0)
        assert lim.allow("k").allowed and lim.allow("k").allowed
        assert not lim.allow("k").allowed
        clock.set(1010.0)  # next window
        assert lim.allow("k").allowed

    def test_windows_wall_clock_aligned(self):
        # Truncation semantics (fixedwindow.go:71-72): window starts at
        # floor(now/window)*window, not at first request.
        lim, clock = make(Algorithm.FIXED_WINDOW, limit=1, window=10.0, start=1008.0)
        assert lim.allow("k").allowed
        clock.set(1011.0)  # only 3s later but into the next aligned window
        assert lim.allow("k").allowed

    def test_reset_at_is_window_end(self):
        lim, _ = make(Algorithm.FIXED_WINDOW, limit=5, window=10.0, start=1003.0)
        res = lim.allow("k")
        assert res.reset_at == pytest.approx(1010.0)

    def test_retry_after_is_time_to_reset(self):
        lim, _ = make(Algorithm.FIXED_WINDOW, limit=1, window=10.0, start=1003.0)
        lim.allow("k")
        res = lim.allow("k")
        assert not res.allowed
        assert res.retry_after == pytest.approx(7.0)


# ------------------------------------------------------------- sliding window

class TestSlidingWindow:
    @pytest.mark.parametrize("progress,expected_weight", [
        (0.0, 1.0), (0.25, 0.75), (0.5, 0.5), (1.0 - 1e-9, 0.0),
    ])
    def test_weighted_count(self, progress, expected_weight):
        # prev*(1-progress)+curr at 0/25/50/100% (slidingwindow_test.go:176-238)
        window = 100.0
        lim, clock = make(Algorithm.SLIDING_WINDOW, limit=100, window=window, start=0.0)
        # Fill previous window with exactly 80.
        assert lim.allow_n("k", 80).allowed
        clock.set(window + progress * window)
        res = lim.allow("k")
        weighted_before = 80 * expected_weight
        assert res.allowed == (weighted_before + 1 <= 100)
        if res.allowed:
            assert res.remaining == 100 - int(weighted_before + 1)

    def test_smooths_boundary_burst(self):
        # The boundary-gaming FW allows (docs/ALGORITHMS.md) is blocked:
        # 100 at end of window + 100 at start of next must not both pass.
        lim, clock = make(Algorithm.SLIDING_WINDOW, limit=100, window=60.0, start=0.0)
        clock.set(59.0)
        assert lim.allow_n("k", 100).allowed
        clock.set(61.0)
        res = lim.allow_n("k", 100)
        assert not res.allowed  # weighted ≈ 100*(1-1/60) ≈ 98.3

    def test_idle_two_windows_clears(self):
        lim, clock = make(Algorithm.SLIDING_WINDOW, limit=5, window=10.0, start=0.0)
        lim.allow_n("k", 5)
        clock.set(25.0)  # skipped a whole window: prev must be 0, not stale
        res = lim.allow_n("k", 5)
        assert res.allowed

    def test_denied_remaining_reports_free_quota(self):
        # Unified remaining semantics (module docstring of exact.py): a
        # denied allow_n(n) with some quota left reports that quota.
        lim, _ = make(Algorithm.SLIDING_WINDOW, limit=10, window=60.0)
        lim.allow_n("k", 8)
        res = lim.allow_n("k", 5)
        assert not res.allowed and res.remaining == 2


# --------------------------------------------------------------- token bucket

class TestTokenBucket:
    def test_starts_full_burst(self):
        # New bucket starts at capacity (tokenbucket.go Lua: `or capacity`).
        lim, _ = make(Algorithm.TOKEN_BUCKET, limit=50, window=60.0)
        assert lim.allow_n("k", 50).allowed
        assert not lim.allow("k").allowed

    def test_continuous_refill(self):
        # rate = limit/window = 1 token/s; fractional refill is continuous,
        # not window-stepped (tokenbucket.go:36-38).
        lim, clock = make(Algorithm.TOKEN_BUCKET, limit=60, window=60.0)
        assert lim.allow_n("k", 60).allowed
        clock.advance(1.5)
        assert lim.allow("k").allowed          # 1.5 tokens accrued
        assert not lim.allow("k").allowed      # only 0.5 left
        clock.advance(0.5)
        assert lim.allow("k").allowed

    def test_refill_caps_at_limit(self):
        lim, clock = make(Algorithm.TOKEN_BUCKET, limit=10, window=10.0)
        lim.allow_n("k", 10)
        clock.advance(1000.0)
        assert lim.allow_n("k", 10).allowed
        assert not lim.allow("k").allowed  # not 10 + surplus

    def test_denial_consumes_nothing(self):
        # The reference TB already honors this (tokenbucket.go:41-45).
        lim, _ = make(Algorithm.TOKEN_BUCKET, limit=10, window=60.0)
        lim.allow_n("k", 8)
        assert not lim.allow_n("k", 5).allowed
        assert lim.allow_n("k", 2).allowed

    def test_retry_after_is_deficit_over_rate(self):
        # retry_after = (n - tokens)/rate (tokenbucket.go:122-130)
        lim, _ = make(Algorithm.TOKEN_BUCKET, limit=60, window=60.0)  # 1 tok/s
        lim.allow_n("k", 60)
        res = lim.allow_n("k", 30)
        assert not res.allowed
        assert res.retry_after == pytest.approx(30.0)

    def test_reset_at_approximation(self):
        # reset_at = now + window (full-fill approximation,
        # tokenbucket.go:161-165) regardless of current level.
        lim, clock = make(Algorithm.TOKEN_BUCKET, limit=10, window=60.0, start=500.0)
        res = lim.allow("k")
        assert res.reset_at == pytest.approx(560.0)

    def test_remaining_is_floor(self):
        lim, clock = make(Algorithm.TOKEN_BUCKET, limit=10, window=10.0)  # 1/s
        lim.allow_n("k", 10)
        clock.advance(2.5)
        res = lim.allow("k")  # 2.5 tokens -> consume 1 -> 1.5 -> floor 1
        assert res.allowed and res.remaining == 1


# ------------------------------------------------------------------ pruning

class TestPrune:
    def test_prune_drops_idle_entries(self):
        lim, clock = make(Algorithm.TOKEN_BUCKET, limit=10, window=10.0)
        lim.allow("a")
        lim.allow("b")
        assert lim.key_count() == 2
        clock.advance(19.0)
        assert lim.prune() == 0      # TTL horizon is 2x window (SURVEY §2.4.9)
        clock.advance(2.0)
        assert lim.prune() == 2
        assert lim.key_count() == 0

    def test_prune_horizons_per_algorithm(self):
        fw, fclock = make(Algorithm.FIXED_WINDOW, limit=10, window=10.0, start=1000.0)
        fw.allow("a")
        fclock.set(1010.0)
        assert fw.prune() == 1       # FW horizon is 1 window

    def test_pruned_key_starts_fresh(self):
        lim, clock = make(Algorithm.TOKEN_BUCKET, limit=5, window=10.0)
        lim.allow_n("k", 5)
        clock.advance(21.0)
        lim.prune()
        assert lim.allow_n("k", 5).allowed  # fresh bucket, full again
