"""Concurrency stress: the lock discipline across the FULL mutable
surface (allow/allow_batch/reset/update_limit/save/restore) — the
closest Python analog of the reference's `go test -race` gate
(SURVEY.md §5.2). Invariants checked are scheduling-independent:
no exceptions, no over-admission past the largest limit ever set, and a
consistent final state."""

from __future__ import annotations

import threading

import numpy as np
import pytest

from ratelimiter_tpu import Algorithm, Config, ManualClock, SketchParams, create_limiter


@pytest.mark.parametrize("backend", ["exact", "dense", "sketch"])
def test_mixed_op_storm(backend, tmp_path):
    clock = ManualClock(1_700_000_000.0)
    cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=50, window=60.0,
                 sketch=SketchParams(depth=2, width=4096, sub_windows=6))
    lim = create_limiter(cfg, backend=backend, clock=clock)
    path = str(tmp_path / "snap.npz")
    lim.save(path)
    errors = []
    barrier = threading.Barrier(8)

    def deciders(wid):
        barrier.wait()
        rng = np.random.default_rng(wid)
        try:
            for i in range(40):
                if i % 7 == 0:
                    lim.allow_batch([f"k{j}" for j in
                                     rng.integers(0, 20, size=16)])
                else:
                    lim.allow(f"k{rng.integers(0, 20)}")
        except Exception as exc:  # noqa: BLE001 - collected for assert
            errors.append(exc)

    def admin():
        barrier.wait()
        try:
            for i in range(12):
                if i % 4 == 0:
                    lim.update_limit(40 + (i % 3) * 10)
                elif i % 4 == 1:
                    lim.reset(f"k{i % 20}")
                elif i % 4 == 2:
                    lim.save(path)
                else:
                    lim.restore(path)
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=deciders, args=(w,)) for w in range(6)]
    threads += [threading.Thread(target=admin) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # restore() mid-storm uses snapshots of a possibly different limit —
    # a CheckpointError from a fingerprint mismatch is the ONLY legal
    # error; anything else (deadlock would hang, races corrupt) fails.
    from ratelimiter_tpu import CheckpointError

    real = [e for e in errors if not isinstance(e, CheckpointError)]
    assert not real, real
    # Limiter is still fully functional and self-consistent.
    lim.update_limit(5)
    lim.reset("post")
    got = sum(lim.allow("post").allowed for _ in range(10))
    assert got == 5
    lim.close()


def test_native_server_storm():
    """The native front door under concurrent mixed clients: no protocol
    desync, health/metrics interleaved with decisions, clean shutdown."""
    from ratelimiter_tpu.serving import Client
    from ratelimiter_tpu.serving.native_server import (
        NativeRateLimitServer,
        native_server_available,
    )

    if not native_server_available():
        pytest.skip("needs g++")
    clock = ManualClock(1_700_000_000.0)
    cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=10_000, window=60.0)
    lim = create_limiter(cfg, backend="exact", clock=clock)
    srv = NativeRateLimitServer(lim, "127.0.0.1", 0, max_delay=1e-3)
    srv.start()
    errors = []

    def client_storm(wid):
        try:
            with Client(port=srv.port) as c:
                for i in range(30):
                    if i % 10 == 0:
                        c.health()
                    elif i % 10 == 5:
                        c.metrics()
                    elif i % 3 == 0:
                        c.allow_batch([f"w{wid}:k{j}" for j in range(8)])
                    else:
                        c.allow(f"w{wid}:k{i}")
                    if i % 13 == 12:
                        c.reset(f"w{wid}:k0")
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=client_storm, args=(w,))
               for w in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
    assert srv.stats()["decisions_total"] > 0
    srv.shutdown()
    lim.close()
