"""build_scan (the multi-step lax.scan runner used by bench phase C and the
micro-batching server): equivalence to single-step dispatches, bit-packing,
and the sub-window-boundary precondition (ADVICE r1)."""

import numpy as np

import jax.numpy as jnp

from ratelimiter_tpu import Algorithm, Config, SketchParams
from ratelimiter_tpu.ops import sketch_kernels


def _cfg(**kw):
    base = dict(algorithm=Algorithm.SLIDING_WINDOW, limit=5, window=6.0,
                max_batch_admission_iters=1,
                sketch=SketchParams(depth=2, width=256, sub_windows=6))
    base.update(kw)
    return Config(**base)


def _fresh(cfg, now_us):
    _, sub_us, _, _, _ = sketch_kernels.sketch_geometry(cfg)
    _, _, roll = sketch_kernels.build_steps(cfg)
    return roll(sketch_kernels.init_state(cfg), jnp.int64(now_us // sub_us))


def _unpack(packed, B):
    bits = np.unpackbits(np.asarray(packed).astype(np.uint8).reshape(-1, B // 8),
                         axis=1, bitorder="little")
    return bits.astype(bool)


T0 = 1_700_000_000 * 1_000_000


def test_scan_equals_sequential_steps():
    cfg = _cfg()
    step, _, _ = sketch_kernels.build_steps(cfg)
    scan = sketch_kernels.build_scan(cfg)
    T, B = 4, 8
    rng = np.random.default_rng(3)
    h1 = rng.integers(0, 2 ** 32, size=(T, B), dtype=np.uint32)
    h2 = rng.integers(0, 2 ** 32, size=(T, B), dtype=np.uint32) | 1
    ns = np.ones((T, B), np.int32)
    dt = 1000  # 1 ms steps, all within one 1 s sub-window

    st = _fresh(cfg, T0)
    st, packed, denies = scan(st, jnp.asarray(h1), jnp.asarray(h2),
                              jnp.asarray(ns), jnp.int64(T0), jnp.int64(dt))
    got = _unpack(packed, B)

    st2 = _fresh(cfg, T0)
    want = []
    for t in range(T):
        st2, (allowed, _, _) = step(st2, jnp.asarray(h1[t]), jnp.asarray(h2[t]),
                                    jnp.asarray(ns[t]), jnp.int64(T0 + t * dt))
        want.append(np.asarray(allowed))
    np.testing.assert_array_equal(got, np.stack(want))
    np.testing.assert_array_equal(np.asarray(denies),
                                  (~np.stack(want)).sum(axis=1))
    # Final states agree too.
    for k in ("cur", "totals"):
        np.testing.assert_array_equal(np.asarray(st[k]), np.asarray(st2[k]))


def test_scan_boundary_precondition_clamps_conservatively():
    """A chunk that crosses a sub-window boundary violates the documented
    precondition. The kernel's clamp (now = max(now, period start)) freezes
    time at the stale period rather than reading rotated state: counts keep
    accumulating in the old sub-window — the error direction is toward
    MORE denies, never over-admission."""
    cfg = _cfg(limit=3)
    scan = sketch_kernels.build_scan(cfg)
    T, B = 3, 8
    h1 = np.full((T, B), 12345, dtype=np.uint32)
    h2 = np.full((T, B), 99991, dtype=np.uint32)
    ns = np.ones((T, B), np.int32)
    st = _fresh(cfg, T0)
    # dt of one full sub-window: steps 2 and 3 land in later periods.
    _, sub_us, _, _, _ = sketch_kernels.sketch_geometry(cfg)
    st, packed, _ = scan(st, jnp.asarray(h1), jnp.asarray(h2), jnp.asarray(ns),
                         jnp.int64(T0), jnp.int64(sub_us))
    got = _unpack(packed, B)
    # limit=3 total admitted across the whole chunk: no quota "refresh" from
    # the skipped rollovers is ever granted.
    assert got.sum() == 3


def test_dense_scan_equals_sequential_steps():
    """dense_kernels.build_scan (benchmark device-time shape): T scanned
    steps produce bit-identical decisions and state to T single-step
    dispatches, for every algorithm."""
    from ratelimiter_tpu.ops import dense_kernels

    for algo in (Algorithm.FIXED_WINDOW, Algorithm.SLIDING_WINDOW,
                 Algorithm.TOKEN_BUCKET):
        cfg = Config(algorithm=algo, limit=5, window=6.0,
                     max_batch_admission_iters=1)
        step = dense_kernels.build_step(cfg)
        scan = dense_kernels.build_scan(cfg)
        T, B, cap = 4, 8, 16
        rng = np.random.default_rng(9)
        sids = rng.integers(0, cap, size=(T, B)).astype(np.int32)
        ns = np.ones((T, B), np.int64)
        dt = 1000

        st = dense_kernels.init_state(algo, cap, cfg.limit)
        st, packed, denies = scan(st, jnp.asarray(sids), jnp.asarray(ns),
                                  jnp.int64(T0), jnp.int64(dt))
        got = _unpack(packed, B)

        st2 = dense_kernels.init_state(algo, cap, cfg.limit)
        want = []
        for t in range(T):
            st2, (allowed, *_rest) = step(st2, jnp.asarray(sids[t]),
                                          jnp.asarray(ns[t]),
                                          jnp.int64(T0 + t * dt))
            want.append(np.asarray(allowed))
        np.testing.assert_array_equal(got, np.stack(want), err_msg=str(algo))
        np.testing.assert_array_equal(np.asarray(denies),
                                      (~np.stack(want)).sum(axis=1))
        for k in st:
            np.testing.assert_array_equal(np.asarray(st[k]),
                                          np.asarray(st2[k]),
                                          err_msg=f"{algo} {k}")


def test_pack_bits_roundtrip():
    mask = np.array([True, False, True, True, False, False, True, False,
                     True, True, True, True, False, False, False, True])
    packed = np.asarray(sketch_kernels._pack_bits(jnp.asarray(mask)))
    np.testing.assert_array_equal(_unpack(packed[None], 16)[0], mask)
