"""Test env: force JAX onto CPU with 8 virtual devices BEFORE jax backends
initialize.

This mirrors how the reference tests distributed behavior without a cluster
(miniredis standing in for Redis, SURVEY.md §4.2): here an 8-device CPU host
platform stands in for a v5e-8 pod so mesh/psum logic runs in CI.

Note: the env var alone is NOT enough on machines with the axon TPU plugin
(it registers regardless); jax.config.update('jax_platforms', ...) is what
actually wins, and it must run before any computation initializes a backend.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax

jax.config.update("jax_platforms", "cpu")
# Device backends require x64 (int64 timestamps / micro-tokens) and no
# longer flip the global at import time (ops.ensure_x64 gates instead) —
# the test env opts in here, once, before any backend initializes.
jax.config.update("jax_enable_x64", True)
# NOTE: deliberately NO persistent compile cache here (bench.py and the
# serving binary do enable one). Measured on this image, concurrent
# compilation from the stress suite's thread storms intermittently
# deadlocks inside the cache's write path (~1 in 3 full runs wedge in
# test_stress_concurrency with every thread parked on the limiter
# lock); cold compiles are slower but deterministic.
