"""Test env: force JAX onto CPU with 8 virtual devices BEFORE jax imports.

This mirrors how the reference tests distributed behavior without a cluster
(miniredis standing in for Redis, SURVEY.md §4.2): here an 8-device CPU host
platform stands in for a v5e-8 pod so mesh/psum logic runs in CI.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
