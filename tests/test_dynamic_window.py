"""Dynamic window updates (update_window): ring-state migration onto a
new sub-window geometry (VERDICT r3 item 10 — the other half of the
dynamic-configuration story; limits shipped in r3)."""

import numpy as np
import pytest

from ratelimiter_tpu import (
    Algorithm,
    Config,
    ManualClock,
    SketchParams,
    create_limiter,
)

T0 = 1_700_000_000.0


def mk(window=6.0, limit=10, sub_windows=6, backend="sketch",
       algo=Algorithm.TPU_SKETCH, **kw):
    cfg = Config(algorithm=algo, limit=limit, window=window,
                 max_batch_admission_iters=4,
                 sketch=SketchParams(depth=2, width=128,
                                     sub_windows=sub_windows, **kw))
    clock = ManualClock(T0)
    return create_limiter(cfg, backend=backend, clock=clock), clock


class TestWindowedMigration:
    def test_consumed_quota_survives_shrink(self):
        """Shrinking the window keeps consumed quota visible (never a
        free refill) until it ages out on the new schedule."""
        lim, clock = mk(window=6.0)
        assert lim.allow_n("k", 10).allowed
        lim.update_window(3.0)
        assert lim.config.window == 3.0
        assert not lim.allow("k").allowed          # no refill from migration
        lim.close()

    def test_consumed_quota_survives_grow(self):
        lim, clock = mk(window=3.0, sub_windows=3)
        assert lim.allow_n("k", 10).allowed
        lim.update_window(12.0)
        assert not lim.allow("k").allowed
        lim.close()

    def test_expiry_follows_new_window(self):
        """After migration, history expires on the NEW window schedule."""
        lim, clock = mk(window=60.0, sub_windows=60)
        assert lim.allow_n("k", 10).allowed
        lim.update_window(3.0)
        clock.advance(4.5)                         # > new window
        assert lim.allow_n("k", 10).allowed        # fully recovered
        lim.close()

    def test_grow_keeps_history_longer(self):
        lim, clock = mk(window=3.0, sub_windows=3)
        assert lim.allow_n("k", 10).allowed
        lim.update_window(30.0)
        clock.advance(5.0)                         # old window would expire
        assert not lim.allow("k").allowed          # new window still holds it
        clock.advance(35.0)
        assert lim.allow("k").allowed
        lim.close()

    def test_never_over_admits_through_migration(self):
        """Error direction: across a migration the total admitted for a
        hot key within any window never exceeds limit (+0 tolerance here
        because migration maps conservatively)."""
        lim, clock = mk(window=6.0, limit=10)
        got = sum(lim.allow("k").allowed for _ in range(8))
        lim.update_window(4.0)
        got += sum(lim.allow("k").allowed for _ in range(8))
        assert got == 10
        lim.close()

    def test_fresh_keys_unaffected(self):
        lim, clock = mk()
        lim.allow_n("a", 10)
        lim.update_window(3.0)
        assert lim.allow_batch(["b"] * 10).allow_count == 10
        lim.close()

    def test_watchdog_ledger_remapped(self):
        lim, clock = mk()
        lim.allow_batch([f"k{i}" for i in range(50)])
        before = lim.in_window_admitted_mass()
        assert before == 50
        lim.update_window(12.0)
        assert lim.in_window_admitted_mass() == 50  # mass carried by time
        lim.close()

    def test_hh_state_migrates(self):
        lim, clock = mk(hh_slots=16, hh_promote_fraction=0.5)
        for _ in range(12):
            lim.allow("hot")                        # promote + cap at 10
        assert np.count_nonzero(np.asarray(lim._state["hh_owner"])) == 1
        lim.update_window(3.0)
        assert not lim.allow("hot").allowed         # private count survived
        assert np.count_nonzero(np.asarray(lim._state["hh_owner"])) == 1
        clock.advance(4.0)
        assert lim.allow("hot").allowed             # new-window expiry
        lim.close()

    def test_retry_and_reset_follow_new_window(self):
        """Denial hints must be computed from the NEW window (a stale
        _window_us would tell clients to wait for the old one)."""
        lim, clock = mk(window=60.0, sub_windows=60)
        lim.allow_n("k", 10)
        lim.update_window(5.0)
        res = lim.allow("k")
        assert not res.allowed
        assert 0 < res.retry_after <= 5.0
        assert res.reset_at <= clock.now() + 5.0
        lim.close()

    def test_mesh_limiters_keep_mesh_steps(self):
        """update_window on a mesh limiter must migrate AND re-install
        the mesh-compiled steps (not silently fall back to single-chip
        kernels) for both algorithm families."""
        import jax

        if len(jax.devices()) < 2:
            pytest.skip("needs a multi-device (CPU) mesh")
        from ratelimiter_tpu.parallel import (
            MeshSketchLimiter,
            MeshTokenBucketLimiter,
            make_mesh,
        )

        mesh = make_mesh()
        cfg = Config(algorithm=Algorithm.TPU_SKETCH, limit=10, window=6.0,
                     max_batch_admission_iters=4,
                     sketch=SketchParams(depth=2, width=128, sub_windows=6))
        lim = MeshSketchLimiter(cfg, mesh=mesh, clock=ManualClock(T0))
        assert lim.allow_batch(["k"] * 16).allow_count == 10
        lim.update_window(3.0)
        out = lim.allow_batch(["k"] * 16)          # mesh batch still works
        assert out.allow_count == 0                # no refill from migration
        lim.close()

        cfg = Config(algorithm=Algorithm.TOKEN_BUCKET, limit=10, window=10.0,
                     sketch=SketchParams(depth=2, width=128))
        clock = ManualClock(T0)
        tb = MeshTokenBucketLimiter(cfg, mesh=mesh, clock=clock)
        assert tb.allow_batch(["k"] * 16).allow_count == 10
        tb.update_window(5.0)
        clock.advance(1.05)                        # 2 tokens at the new rate
        assert tb.allow_batch(["k"] * 4).allow_count == 2
        tb.close()

    def test_geometry_change_rejected(self):
        from ratelimiter_tpu import InvalidConfigError
        from ratelimiter_tpu.ops import sketch_kernels

        lim, _ = mk()
        cfg2 = Config(algorithm=Algorithm.TPU_SKETCH, limit=10, window=3.0,
                      sketch=SketchParams(depth=3, width=128, sub_windows=6))
        with pytest.raises(InvalidConfigError):
            sketch_kernels.build_migrate(lim.config, cfg2)
        lim.close()

    def test_invalid_window_rejected(self):
        from ratelimiter_tpu import InvalidConfigError

        lim, _ = mk()
        with pytest.raises(InvalidConfigError):
            lim.update_window(0.0)
        lim.close()


class TestBucketWindowUpdate:
    def test_rate_changes_debt_stands(self):
        """window sets the refill rate; debt carries across the update."""
        lim, clock = mk(algo=Algorithm.TOKEN_BUCKET, window=10.0, limit=10)
        assert lim.allow_n("k", 10).allowed         # drained
        lim.update_window(5.0)                      # refill 2x faster now
        assert not lim.allow("k").allowed
        clock.advance(1.1)                          # ~2.2 tokens at new rate
        assert lim.allow_n("k", 2).allowed
        assert not lim.allow("k").allowed
        lim.close()

class TestExactDenseWindowUpdate:
    """update_window on the exact (host dict) and dense (slot-addressed
    device) backends — same contract the sketch migration pins above:
    consumption stands, re-expiry on the NEW schedule, never a free
    refill (VERDICT r4 item 7)."""

    BACKENDS = ["exact", "dense"]

    @pytest.mark.parametrize("backend", BACKENDS)
    @pytest.mark.parametrize("algo", [Algorithm.SLIDING_WINDOW,
                                      Algorithm.FIXED_WINDOW])
    def test_consumed_quota_survives_shrink(self, backend, algo):
        lim, clock = mk(window=6.0, backend=backend, algo=algo)
        assert lim.allow_n("k", 10).allowed
        lim.update_window(3.0)
        assert lim.config.window == 3.0
        assert not lim.allow("k").allowed          # no refill from migration
        lim.close()

    @pytest.mark.parametrize("backend", BACKENDS)
    @pytest.mark.parametrize("algo", [Algorithm.SLIDING_WINDOW,
                                      Algorithm.FIXED_WINDOW])
    def test_consumed_quota_survives_grow(self, backend, algo):
        lim, clock = mk(window=3.0, backend=backend, algo=algo)
        assert lim.allow_n("k", 10).allowed
        lim.update_window(12.0)
        assert not lim.allow("k").allowed
        lim.close()

    @pytest.mark.parametrize("backend", BACKENDS)
    def test_expiry_follows_new_window(self, backend):
        lim, clock = mk(window=60.0, backend=backend,
                        algo=Algorithm.SLIDING_WINDOW)
        assert lim.allow_n("k", 10).allowed
        lim.update_window(3.0)
        clock.advance(6.5)                         # > 2 new windows
        assert lim.allow_n("k", 10).allowed        # fully recovered
        lim.close()

    @pytest.mark.parametrize("backend", BACKENDS)
    def test_grow_keeps_history_longer(self, backend):
        lim, clock = mk(window=3.0, backend=backend,
                        algo=Algorithm.SLIDING_WINDOW)
        assert lim.allow_n("k", 10).allowed
        lim.update_window(30.0)
        clock.advance(5.0)                         # old window would expire
        assert not lim.allow("k").allowed          # new one keeps history
        lim.close()

    @pytest.mark.parametrize("backend", BACKENDS)
    def test_fresh_and_stale_keys(self, backend):
        """Keys idle past the old window migrate as dead; fresh keys are
        unaffected by the migration."""
        lim, clock = mk(window=3.0, backend=backend,
                        algo=Algorithm.SLIDING_WINDOW)
        assert lim.allow_n("old", 10).allowed
        clock.advance(7.0)                         # "old" fully expired
        lim.update_window(30.0)
        assert lim.allow_n("old", 10).allowed      # no resurrection
        assert lim.allow_n("fresh", 10).allowed
        lim.close()

    @pytest.mark.parametrize("backend", BACKENDS)
    def test_bucket_rate_changes_level_stands(self, backend):
        lim, clock = mk(algo=Algorithm.TOKEN_BUCKET, window=10.0,
                        backend=backend)
        assert lim.allow_n("k", 10).allowed        # drained
        lim.update_window(5.0)                     # refill 2x faster now
        assert not lim.allow("k").allowed
        clock.advance(1.1)                         # ~2.2 tokens at new rate
        assert lim.allow_n("k", 2).allowed
        assert not lim.allow("k").allowed
        lim.close()

    def test_exact_matches_dense_through_migration(self):
        """Cross-backend agreement survives a window migration (the
        bit-exactness contract of tests/test_cross_backend.py)."""
        le, ce = mk(window=6.0, backend="exact",
                    algo=Algorithm.SLIDING_WINDOW)
        ld, cd = mk(window=6.0, backend="dense",
                    algo=Algorithm.SLIDING_WINDOW)
        for lim in (le, ld):
            assert lim.allow_n("a", 7).allowed
            assert lim.allow_n("b", 10).allowed
        for lim in (le, ld):
            lim.update_window(9.0)
        for dt in (0.0, 2.0, 4.0, 9.5):
            ce.advance(dt)
            cd.advance(dt)
            for key in ("a", "b", "c"):
                re = le.allow(key)
                rd = ld.allow(key)
                assert (re.allowed, re.remaining) == \
                    (rd.allowed, rd.remaining), (key, dt)
        le.close()
        ld.close()
