"""Metric-name drift gate (ADR-021 satellite): OPERATIONS §3 is the
monitoring CONTRACT, so it must match what servers actually export —
in BOTH directions.

Three real server binaries (spawned concurrently) cover the
backend-conditional families:

* a fully-featured windowed-sketch member (fleet + audit + hh +
  flight recorder + breaker + tenants + controller + persistence +
  leases) — the bulk of the families, incl. the sketch accuracy
  envelope and the ADR-022 lease families;
* a mesh member with quarantine — the per-slice failure-domain
  families;
* a token-bucket server behind the NATIVE door — the debt-slab
  families plus the multi-ring network-engine families (ADR-026:
  engine info, syscall ledger, writev batch factor).

Direction 1: every `rate_limiter_*` name written in OPERATIONS §3 must
exist in the union scrape (a documented name may also be a PREFIX of a
scraped family — the `rate_limiter_audit_slice_*` glob idiom).
Direction 2: every scraped family must appear somewhere in
OPERATIONS.md. A renamed/dropped/added-but-undocumented metric fails
here instead of silently breaking dashboards.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import time
import urllib.request

import pytest

from netutil import free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OPERATIONS = os.path.join(REPO, "docs", "OPERATIONS.md")


def _spawn(argv_extra, env_extra=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + env.get("PYTHONPATH", "").split(os.pathsep))
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra or {})
    argv = [sys.executable, "-m", "ratelimiter_tpu.serving",
            "--sketch-depth", "2", "--sketch-width", "1024",
            "--no-prewarm", "--max-batch", "256", *argv_extra]
    return subprocess.Popen(argv, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL, text=True)


def _await_banner(proc):
    line = proc.stdout.readline()
    if "serving" not in line:
        raise RuntimeError(f"server failed to start: {line!r}")


def _scrape(http_port: int) -> str:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{http_port}/metrics", timeout=10) as r:
        return r.read().decode()


def _families(text: str) -> set:
    return set(re.findall(r"# TYPE (\S+) ", text))


@pytest.mark.slow
class TestMetricNameDrift:
    def test_operations_section3_matches_scrape_both_directions(
            self, tmp_path):
        ports = [free_port() for _ in range(3)]
        https = [free_port() for _ in range(3)]
        cfgpath = os.path.join(str(tmp_path), "fleet.json")
        with open(cfgpath, "w", encoding="utf-8") as f:
            json.dump({"buckets": 32, "epoch": 1, "hosts": [
                {"id": "h0", "host": "127.0.0.1", "port": ports[0],
                 "http": https[0], "ranges": [[0, 32]]}]}, f)
        snap = os.path.join(str(tmp_path), "snap")
        procs = [
            # 1: featured windowed-sketch fleet member.
            _spawn(["--backend", "sketch", "--sub-windows", "6",
                    "--port", str(ports[0]),
                    "--http-port", str(https[0]),
                    "--fleet-config", cfgpath, "--fleet-self", "h0",
                    "--flight-recorder", "--debug-token", "tok",
                    "--audit", "--audit-sample", "1",
                    "--hh-slots", "16", "--circuit-breaker",
                    "--tenants", "4", "--global-limit", "1000",
                    "--controller", "--snapshot-dir", snap,
                    "--leases",
                    "--http-rebalance-token", "rtok",
                    "--http-policy-token", "ptok"]),
            # 2: mesh + quarantine (per-slice failure domains).
            _spawn(["--backend", "mesh", "--mesh-devices", "2",
                    "--quarantine", "--sub-windows", "6",
                    "--port", str(ports[1]),
                    "--http-port", str(https[1])],
                   {"XLA_FLAGS":
                    "--xla_force_host_platform_device_count=2"}),
            # 3: token bucket (debt-slab families) behind the NATIVE
            # door (multi-ring net engine families, ISSUE-20).
            _spawn(["--algorithm", "token_bucket", "--backend",
                    "sketch", "--native", "--port", str(ports[2]),
                    "--http-port", str(https[2])]),
        ]
        try:
            for proc in procs:
                _await_banner(proc)
            # One policy mutation: the override-occupancy gauge
            # registers on first set (documented §3 family).
            req = urllib.request.Request(
                f"http://127.0.0.1:{https[0]}/v1/policy?key=k&limit=5",
                method="POST")
            req.add_header("Authorization", "Bearer ptok")
            urllib.request.urlopen(req, timeout=10).read()
            time.sleep(0.3)
            fams = set()
            for hp in https:
                fams |= _families(_scrape(hp))
        finally:
            for proc in procs:
                proc.terminate()
            for proc in procs:
                try:
                    proc.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    proc.kill()

        assert len(fams) > 50, f"suspiciously small scrape: {fams}"
        with open(OPERATIONS, encoding="utf-8") as f:
            ops = f.read()
        sec3 = re.search(r"\n## 3\. What to monitor(.*?)\n## 4\.",
                         ops, re.S).group(1)
        doc3 = set(re.findall(r"rate_limiter_[a-z0-9_]*[a-z0-9]",
                              sec3))

        # Direction 1: everything §3 names is really exported (exact
        # family, or a prefix — the `..._slice_*` glob idiom).
        missing = sorted(
            n for n in doc3
            if n not in fams
            and not any(f.startswith(n + "_") for f in fams))
        assert not missing, (
            f"OPERATIONS §3 documents families no server exports "
            f"(renamed? dropped?): {missing}")

        # Direction 2: everything exported is documented SOMEWHERE in
        # OPERATIONS.md.
        undocumented = sorted(n for n in fams if n not in ops)
        assert not undocumented, (
            f"servers export families OPERATIONS.md never mentions "
            f"(add a §3 row): {undocumented}")
