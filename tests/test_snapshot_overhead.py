"""Durability overhead smoke (ISSUE-2 satellite): background
snapshotting must keep p99 allow latency within budget of the
no-persistence baseline — guarding the off-lock serialization claim
(persistence/snapshotter.py: only the device→host capture holds the
limiter lock; serialization + fsync happen off-lock).

Runs bench.py's phase E (measure_snapshot_overhead) at a small shape.
The budget is deliberately generous — CI boxes are noisy and a single
shared CPU makes even off-lock work steal cycles — but an on-lock
serialization regression at this state size (~6 MB npz + fsync per
snapshot, every 0.25 s) blocks dispatches for hundreds of ms and blows
far past it.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def test_p99_within_budget_of_baseline(tmp_path):
    from bench import measure_snapshot_overhead

    out = measure_snapshot_overhead(
        0.25, snapshot_dir=str(tmp_path), seconds=2.0,
        depth=3, width=1 << 14, sub_windows=60)
    base = out["baseline"]
    snap = out["with_snapshots"]
    assert snap["snapshots_taken"] >= 1, out     # the thread actually ran
    assert base["dispatches"] > 50 and snap["dispatches"] > 50, out
    budget_ms = max(5.0 * base["p99_ms"], base["p99_ms"] + 250.0)
    assert snap["p99_ms"] <= budget_ms, (
        f"background snapshotting pushed p99 from {base['p99_ms']}ms to "
        f"{snap['p99_ms']}ms (budget {budget_ms:.1f}ms) — is "
        f"serialization running under the limiter lock? {out}")
    # The median must be essentially untouched: snapshots are rare
    # events, so any broad shift means constant overhead leaked into
    # the decision path.
    assert snap["p50_ms"] <= 3.0 * base["p50_ms"] + 5.0, out
