"""Durability overhead smoke (ISSUE-2 satellite): background
snapshotting must keep p99 allow latency within budget of the
no-persistence baseline — guarding the off-lock serialization claim
(persistence/snapshotter.py: only the device→host capture holds the
limiter lock; serialization + fsync happen off-lock).

Runs bench.py's phase E (measure_snapshot_overhead) at a small shape.
The budget is deliberately generous — CI boxes are noisy and a single
shared CPU makes even off-lock work steal cycles — but an on-lock
serialization regression at this state size (~6 MB npz + fsync per
snapshot, every 0.25 s) blocks dispatches for hundreds of ms and blows
far past it.

Deflaked for ISSUE-20: the latency assertions are gated on a
LOAD-QUIET check (1-minute loadavg sampled before and after the
measurement). A busy box — e.g. a concurrent bench run on the same CI
host — turns a budget miss into a skip with the measured numbers in
the reason, never a spurious red; the structural assertions (the
snapshot thread ran, dispatches flowed) hold regardless. A budget miss
on a QUIET box still fails loudly — that is the regression the test
exists to catch.
"""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _box_quiet() -> bool:
    """True when the 1-minute loadavg leaves headroom for the bench:
    concurrent load (another test lane, a bench run) shows up here and
    makes tail-latency budgets meaningless."""
    try:
        la1 = os.getloadavg()[0]
    except OSError:  # pragma: no cover - non-POSIX
        return True
    return la1 <= (os.cpu_count() or 1) + 0.5


def test_p99_within_budget_of_baseline(tmp_path):
    from bench import measure_snapshot_overhead

    quiet_before = _box_quiet()
    out = measure_snapshot_overhead(
        0.25, snapshot_dir=str(tmp_path), seconds=2.0,
        depth=3, width=1 << 14, sub_windows=60)
    quiet_after = _box_quiet()
    base = out["baseline"]
    snap = out["with_snapshots"]
    # Structural invariants hold on any box, loaded or not.
    assert snap["snapshots_taken"] >= 1, out     # the thread actually ran
    assert base["dispatches"] > 50 and snap["dispatches"] > 50, out
    budget_ms = max(5.0 * base["p99_ms"], base["p99_ms"] + 250.0)
    p50_ok = snap["p50_ms"] <= 3.0 * base["p50_ms"] + 5.0
    p99_ok = snap["p99_ms"] <= budget_ms
    if not (p99_ok and p50_ok) and not (quiet_before and quiet_after):
        pytest.skip(
            f"latency budget not assertable under concurrent load "
            f"(loadavg {os.getloadavg()[0]:.1f} on "
            f"{os.cpu_count()} cpus): base p99={base['p99_ms']}ms "
            f"snap p99={snap['p99_ms']}ms budget={budget_ms:.1f}ms")
    assert p99_ok, (
        f"background snapshotting pushed p99 from {base['p99_ms']}ms to "
        f"{snap['p99_ms']}ms (budget {budget_ms:.1f}ms) — is "
        f"serialization running under the limiter lock? {out}")
    # The median must be essentially untouched: snapshots are rare
    # events, so any broad shift means constant overhead leaked into
    # the decision path.
    assert p50_ok, out
