"""Hierarchical cascades + adaptive control (ratelimiter_tpu/hierarchy/,
ADR-020).

Pins the cascade contract the kernels document (ops/hier_kernels.py):

* per-scope oracle pinning — cascade decisions bit-identical to a
  sequential key → tenant → global reference limiter (per-request
  traces) and to the staged in-batch reference (randomized batches);
* weighted fair sharing — contended global mass clipped proportionally
  to tenant weights, exact integer caps;
* all-or-nothing — a request denied at a later scope consumes nothing
  at any scope;
* the AIMD controller converging (tighten under a seeded hot-tenant
  storm, additive recovery after it clears);
* durability — tenant registry, assignments, and controller-moved
  effective limits ride checkpoints; enabled-geometry mismatches refuse;
* mesh twins — sliced (per-slice share divisor) and replicated
  (collective) cascade enforcement.

Doors (HTTP gateway /v1/tenants, native server, the migrate surface)
live in tests/test_hierarchy_serving.py.
"""

from __future__ import annotations

from collections import defaultdict

import numpy as np
import pytest

from ratelimiter_tpu import (
    Algorithm,
    CheckpointError,
    Config,
    HierarchySpec,
    InvalidConfigError,
    ManualClock,
    create_limiter,
)
from ratelimiter_tpu.core.config import HIER_UNLIMITED, SketchParams
from ratelimiter_tpu.hierarchy import (
    GLOBAL,
    AIMDController,
    AIMDGains,
    HierarchyFanout,
    TenantTable,
)

T0 = 1_700_000_000.0


def make(limit=1_000_000, window=60.0, *, tenants=8, map_capacity=128,
         global_limit=0, default_tenant_limit=0,
         algo=Algorithm.SLIDING_WINDOW, backend="sketch", **kw):
    clock = ManualClock(T0)
    cfg = Config(
        algorithm=algo, limit=limit, window=window,
        sketch=SketchParams(depth=3, width=1 << 14, sub_windows=4),
        hierarchy=HierarchySpec(tenants=tenants, map_capacity=map_capacity,
                                global_limit=global_limit,
                                default_tenant_limit=default_tenant_limit),
        **kw)
    return create_limiter(cfg, backend=backend, clock=clock), clock


# ------------------------------------------------------------- spec + table


class TestSpecAndTable:
    def test_spec_validation(self):
        for bad in ({"tenants": 3}, {"tenants": 1}, {"tenants": 1 << 13},
                    {"map_capacity": 7}, {"map_capacity": 3},
                    {"global_limit": -1}, {"global_limit": HIER_UNLIMITED},
                    {"default_tenant_limit": -5}):
            with pytest.raises(InvalidConfigError):
                Config(algorithm=Algorithm.SLIDING_WINDOW, limit=4,
                       window=60.0,
                       hierarchy=HierarchySpec(**{"tenants": 4, **bad}),
                       ).validate()

    def test_disabled_backend_raises(self):
        clock = ManualClock(T0)
        cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=4,
                     window=60.0)
        lim = create_limiter(cfg, backend="sketch", clock=clock)
        with pytest.raises(NotImplementedError, match="hierarchy"):
            lim.set_tenant("acme", 10)
        lim.close()

    def test_tenant_validation(self):
        lim, _ = make(tenants=2)  # capacity 2: default + one more
        with pytest.raises(InvalidConfigError):
            lim.set_tenant("", 10)
        with pytest.raises(InvalidConfigError):
            lim.set_tenant("a", -1)
        with pytest.raises(InvalidConfigError):
            lim.set_tenant("a", 10, weight=0)
        with pytest.raises(InvalidConfigError):
            lim.set_tenant("a", 10, floor=11)  # floor > ceiling
        lim.set_tenant("a", 10)
        with pytest.raises(InvalidConfigError, match="full"):
            lim.set_tenant("b", 10)
        with pytest.raises(InvalidConfigError):
            lim.assign_tenant("k", "nope")
        with pytest.raises(InvalidConfigError):
            lim.delete_tenant("default")
        lim.close()

    def test_map_capacity_enforced(self):
        lim, _ = make(tenants=4, map_capacity=8)
        lim.set_tenant("t", 10)
        for i in range(8):
            lim.assign_tenant(f"k{i}", "t")
        with pytest.raises(InvalidConfigError, match="map full"):
            lim.assign_tenant("k8", "t")
        # Re-assigning an existing key is not growth.
        lim.assign_tenant("k0", "t")
        assert lim.unassign_tenant("k0")
        lim.assign_tenant("k8", "t")
        lim.close()

    def test_delete_falls_back_to_default(self):
        lim, _ = make()
        lim.set_tenant("t", 10)
        lim.assign_tenant("k", "t")
        assert lim.tenant_of("k") == "t"
        assert lim.delete_tenant("t")
        assert lim.tenant_of("k") == "default"
        lim.close()

    def test_effective_clamped_to_floor_and_ceiling(self):
        lim, _ = make()
        lim.set_tenant("t", 100, floor=20)
        assert lim.set_effective("t", 5) == 20        # floor clamp
        assert lim.set_effective("t", 10_000) == 100  # ceiling clamp
        assert lim.set_effective("t", 60) == 60
        assert lim.effective_limits()["t"] == 60
        # Lowering the ceiling drags an out-of-range effective down.
        lim.set_tenant("t", 50, floor=20)
        assert lim.effective_limits()["t"] == 50
        lim.close()

    def test_payload_last_writer_wins(self):
        a, _ = make(global_limit=100)
        b, _ = make(global_limit=100)
        for lim in (a, b):
            lim.set_tenant("t", 50)
        a.set_effective("t", 25)
        payload = a.hierarchy_payload()
        assert b.apply_hierarchy_payload(payload)
        assert b.effective_limits()["t"] == 25
        # Same revision again: stale, refused.
        assert not b.apply_hierarchy_payload(payload)
        # Unknown tenants in a newer frame are skipped, not fatal.
        assert b.apply_hierarchy_payload(
            {"revision": 99, "effective": {"ghost": 1, "t": 30}})
        assert b.effective_limits()["t"] == 30
        a.close()
        b.close()

    def test_adoption_lands_exactly_at_peer_revision(self):
        """Adopting a multi-scope frame must not inflate the local
        revision past the peer's (each set_effective bumps it): an
        inflated revision would reject the origin's NEXT move and LWW
        would roll the fleet back to stale limits."""
        a, _ = make(global_limit=100)
        b, _ = make(global_limit=100)
        for lim in (a, b):
            lim.set_tenant("t1", 50)
            lim.set_tenant("t2", 60)
        a.set_effective("t1", 25)
        a.set_effective("t2", 30)
        a.set_effective(GLOBAL, 80)          # a at revision 3
        assert b.apply_hierarchy_payload(a.hierarchy_payload())
        # b adopted 3 scopes but sits AT rev 3, not 3 + bumps.
        assert b.hierarchy_payload()["revision"] == 3
        # ... so a's next single move (rev 4) is adopted, not refused.
        a.set_effective("t1", 20)
        assert b.apply_hierarchy_payload(a.hierarchy_payload())
        assert b.effective_limits()["t1"] == 20
        a.close()
        b.close()


# --------------------------------------------------- sequential oracle pin


class SequentialReference:
    """Sequential key → tenant → global reference limiter: each request
    is allowed iff ALL three scopes have room, and consumes at all three
    iff allowed (the per-request cascade contract)."""

    def __init__(self, key_limit, tenant_limits, global_limit):
        self.key_limit = key_limit
        self.tenant_limits = tenant_limits    # name -> limit (None = unl)
        self.global_limit = global_limit      # None = unlimited
        self.keys = defaultdict(int)
        self.tenants = defaultdict(int)
        self.total = 0

    def allow(self, key, tenant, n=1):
        tl = self.tenant_limits.get(tenant)
        ok = (self.keys[key] + n <= self.key_limit
              and (tl is None or self.tenants[tenant] + n <= tl)
              and (self.global_limit is None
                   or self.total + n <= self.global_limit))
        if ok:
            self.keys[key] += n
            self.tenants[tenant] += n
            self.total += n
        return ok


@pytest.mark.parametrize("algo", [Algorithm.SLIDING_WINDOW,
                                  Algorithm.FIXED_WINDOW,
                                  Algorithm.TOKEN_BUCKET])
def test_sequential_oracle_pinning(algo):
    """Per-request cascade decisions bit-identical to the sequential
    reference across a seeded mixed trace (both sketch backends)."""
    tenant_limits = {"a": 15, "b": 9, "default": 30}
    lim, _ = make(limit=12, algo=algo, global_limit=40,
                  default_tenant_limit=30)
    lim.set_tenant("a", 15)
    lim.set_tenant("b", 9)
    keys = [f"k{i}" for i in range(12)]
    tenant_of = {}
    for i, k in enumerate(keys):
        t = ("a", "b", "default")[i % 3]
        tenant_of[k] = t
        if t != "default":
            lim.assign_tenant(k, t)
    ref = SequentialReference(12, tenant_limits, 40)
    rng = np.random.default_rng(7)
    trace = rng.integers(0, len(keys), size=300)
    mismatches = []
    for step, ki in enumerate(trace):
        k = keys[int(ki)]
        got = lim.allow(k).allowed
        want = ref.allow(k, tenant_of[k])
        if got != want:
            mismatches.append((step, k, got, want))
    assert not mismatches, mismatches[:10]
    st = lim.hierarchy_stats()
    assert st["global"]["in_window"] == ref.total
    for name in ("a", "b", "default"):
        assert st["tenants"][name]["in_window"] == ref.tenants[name]
    lim.close()


# ---------------------------------------------------- staged batch oracle


def staged_reference(tids, ns, avail_tn, g_avail, weights):
    """Host model of ops/hier_kernels.cascade_admit stages 2+3 (stage 1
    assumed all-pass: key limits set far above any demand)."""
    B = len(tids)
    cum = defaultdict(int)
    surv = []
    for i in range(B):
        t = int(tids[i])
        ok = cum[t] + ns[i] <= avail_tn[t]
        if ok:
            cum[t] += ns[i]
        surv.append(ok)
    demand = defaultdict(int)
    for i in range(B):
        if surv[i]:
            demand[int(tids[i])] += ns[i]
    total = sum(demand.values())
    if total > g_avail:
        active = [t for t, d in demand.items() if d > 0]
        w_sum = max(sum(weights[t] for t in active), 1)
        cap = {t: min(d, g_avail * weights[t] // w_sum)
               for t, d in demand.items()}
    else:
        cap = dict(demand)
    cum3 = defaultdict(int)
    out = []
    for i in range(B):
        t = int(tids[i])
        ok = surv[i] and cum3[t] + ns[i] <= cap.get(t, 0)
        if ok:
            cum3[t] += ns[i]
        out.append(ok)
    return out


@pytest.mark.parametrize("seed", [1, 2, 3])
@pytest.mark.parametrize("algo", [Algorithm.SLIDING_WINDOW,
                                  Algorithm.TOKEN_BUCKET])
def test_batch_staged_oracle(algo, seed):
    """Randomized single batches bit-identical to the documented staged
    semantics (tenant greedy over key survivors, then weighted fair
    share of the global scope)."""
    rng = np.random.default_rng(seed)
    T = 8
    names = [f"t{j}" for j in range(1, T - 1)]  # leave slack capacity
    tn_limit = {j + 1: int(rng.integers(3, 25)) for j in range(len(names))}
    tn_weight = {j + 1: int(rng.integers(1, 6)) for j in range(len(names))}
    g_limit = int(rng.integers(10, 40))
    lim, _ = make(tenants=T, map_capacity=128, global_limit=g_limit,
                  default_tenant_limit=17, algo=algo)
    for j, name in enumerate(names):
        lim.set_tenant(name, tn_limit[j + 1], weight=tn_weight[j + 1])
    B = 64
    keys = [f"k{i}" for i in range(B)]
    tids = rng.integers(0, len(names) + 1, size=B)  # 0 = default tenant
    for k, t in zip(keys, tids):
        if t > 0:
            lim.assign_tenant(k, names[int(t) - 1])
    ns = rng.integers(1, 4, size=B).astype(int).tolist()
    out = lim.allow_batch(keys, ns)
    avail_tn = {0: 17, **tn_limit}
    weights = {0: 1, **tn_weight}
    want = staged_reference(tids, ns, avail_tn, g_limit, weights)
    got = [bool(x) for x in out.allowed]
    assert got == want, [
        (i, int(tids[i]), ns[i], got[i], want[i])
        for i in range(B) if got[i] != want[i]]
    st = lim.hierarchy_stats()
    assert st["global"]["in_window"] == sum(
        n for n, a in zip(ns, want) if a)
    lim.close()


# --------------------------------------------------------------- fair share


class TestFairShare:
    def test_contended_mass_clipped_by_weight(self):
        """G=40 contended 3:1 → caps 30/10 exactly (floor division)."""
        lim, _ = make(tenants=4, global_limit=40)
        lim.set_tenant("gold", 1000, weight=3)
        lim.set_tenant("bronze", 1000, weight=1)
        keys, ns = [], []
        for i in range(50):
            for t in ("gold", "bronze"):
                k = f"{t}{i}"
                lim.assign_tenant(k, t)
                keys.append(k)
                ns.append(1)
        out = lim.allow_batch(keys, ns)
        st = lim.hierarchy_stats()
        assert st["tenants"]["gold"]["in_window"] == 30
        assert st["tenants"]["bronze"]["in_window"] == 10
        assert st["global"]["in_window"] == 40
        assert int(out.allowed.sum()) == 40
        lim.close()

    def test_inactive_tenants_excluded_from_share(self):
        """Idle tenants' weights do not dilute active tenants' shares."""
        lim, _ = make(tenants=8, global_limit=40)
        lim.set_tenant("busy", 1000, weight=1)
        lim.set_tenant("idle", 1000, weight=100)
        keys = []
        for i in range(60):
            k = f"b{i}"
            lim.assign_tenant(k, "busy")
            keys.append(k)
        out = lim.allow_batch(keys)
        # Only 'busy' demands: its share is the whole global availability
        # even though 'idle' carries a huge weight.
        assert int(out.allowed.sum()) == 40
        lim.close()

    def test_uncontended_demand_all_admitted(self):
        lim, _ = make(tenants=4, global_limit=100)
        lim.set_tenant("a", 1000, weight=1)
        lim.set_tenant("b", 1000, weight=9)
        keys = []
        for i in range(20):
            for t in ("a", "b"):
                k = f"{t}{i}"
                lim.assign_tenant(k, t)
                keys.append(k)
        out = lim.allow_batch(keys)
        assert int(out.allowed.sum()) == 40  # 40 <= 100: nobody clipped
        lim.close()


# ------------------------------------------------------------ all-or-nothing


class TestAllOrNothing:
    def test_cascade_denial_consumes_nothing(self):
        """Requests denied at the global scope must not burn key or
        tenant quota: after the global effective limit is relaxed, the
        key's full remaining quota is still there."""
        lim, _ = make(limit=5, tenants=4, global_limit=100)
        lim.set_tenant("t", 50)
        lim.assign_tenant("k", "t")
        assert lim.set_effective(GLOBAL, 10) == 10
        fill = [f"f{i}" for i in range(10)]
        assert int(lim.allow_batch(fill).allowed.sum()) == 10
        # Global exhausted: every 'k' attempt denies...
        for _ in range(4):
            assert not lim.allow("k").allowed
        # ...and consumed NOTHING at the key or tenant scope.
        st = lim.hierarchy_stats()
        assert st["tenants"]["t"]["in_window"] == 0
        lim.set_effective(GLOBAL, 100)
        got = sum(lim.allow("k").allowed for _ in range(7))
        assert got == 5  # the key's whole limit, untouched by the denials
        lim.close()

    def test_tenant_denial_preserves_key_quota(self):
        lim, _ = make(limit=8, tenants=4)
        lim.set_tenant("t", 3, floor=1)
        lim.assign_tenant("k", "t")
        assert sum(lim.allow("k").allowed for _ in range(6)) == 3
        st = lim.hierarchy_stats()
        assert st["tenants"]["t"]["in_window"] == 3
        # Raise the tenant ceiling: key quota (8 - 3 = 5) still intact.
        lim.set_tenant("t", 100)
        assert sum(lim.allow("k").allowed for _ in range(8)) == 5
        lim.close()


# -------------------------------------------------------- windows + retry


class TestWindows:
    def test_windowed_tenant_counters_decay(self):
        lim, clock = make(limit=1000, tenants=4, global_limit=10,
                          window=60.0)
        keys = [f"k{i}" for i in range(20)]
        assert int(lim.allow_batch(keys).allowed.sum()) == 10
        # Sliding window: advance past the window AND its boundary
        # sub-window (whose mass still counts, frac-weighted).
        clock.advance(121.0)
        assert int(lim.allow_batch(keys).allowed.sum()) == 10
        lim.close()

    def test_bucket_cascade_retry_at_window_boundary(self):
        lim, clock = make(limit=1000, tenants=4, global_limit=5,
                          window=60.0, algo=Algorithm.TOKEN_BUCKET)
        keys = [f"k{i}" for i in range(5)]
        assert int(lim.allow_batch(keys).allowed.sum()) == 5
        res = lim.allow("fresh")
        assert not res.allowed
        # Key scope has full tokens (deficit 0): the retry hint is the
        # tenant/global fixed-window boundary, not the refill formula.
        boundary = 60.0 - (T0 % 60.0)
        assert res.retry_after == pytest.approx(boundary, abs=1e-3)
        clock.advance(boundary + 0.5)
        assert lim.allow("fresh").allowed
        lim.close()

    def test_key_reset_leaves_tenant_counters(self):
        """reset() forgives the KEY only — aggregate tenant/global
        accounting stands (a reset-hammering key cannot drain its
        tenant, ADR-020)."""
        lim, _ = make(limit=4, tenants=4, global_limit=100)
        lim.set_tenant("t", 50)
        lim.assign_tenant("k", "t")
        assert sum(lim.allow("k").allowed for _ in range(4)) == 4
        lim.reset("k")
        st = lim.hierarchy_stats()
        assert st["tenants"]["t"]["in_window"] == 4
        assert sum(lim.allow("k").allowed for _ in range(6)) == 4
        assert lim.hierarchy_stats()["tenants"]["t"]["in_window"] == 8
        lim.close()


# ------------------------------------------------------- AIMD controller


class TestController:
    GAINS = AIMDGains(decrease_factor=0.5, increase_fraction=0.25,
                      saturation=0.9, hot_share=2.0, cooldown_s=0.0)

    def _storm_limiter(self):
        lim, clock = make(limit=100_000, tenants=4, global_limit=100)
        lim.set_tenant("attacker", 1000, weight=1, floor=5)
        lim.set_tenant("victim", 1000, weight=6, floor=5)
        for i in range(40):
            lim.assign_tenant(f"a{i}", "attacker")
        for i in range(8):
            lim.assign_tenant(f"v{i}", "victim")
        return lim, clock

    def test_converges_on_seeded_storm(self):
        """Hot-tenant storm: the controller tightens the HOT tenant
        (never the victim), then additively recovers to the ceiling
        after the storm clears."""
        lim, clock = self._storm_limiter()
        ctl = AIMDController(lim, gains=self.GAINS, interval=999)
        # Storm: attacker floods 90+ of the 100 global; victim trickles.
        lim.allow_batch([f"a{i}" for i in range(40)] * 3)   # 120 demanded
        lim.allow_batch([f"v{i}" for i in range(8)])
        st = lim.hierarchy_stats()
        assert st["global"]["in_window"] >= 90  # saturated
        now = 0.0
        moved = ctl.tick(now)
        assert "attacker" in moved
        assert "victim" not in moved and GLOBAL not in moved
        assert moved["attacker"] == 500  # 1000 * 0.5
        assert ctl.tightened == 1
        # Second tick while still saturated: tighten again (cooldown 0).
        moved = ctl.tick(now + 1)
        assert moved.get("attacker") == 250
        # Storm ends; window (and its boundary sub-window) rolls; a
        # throwaway decision kicks the rollover sweep that recomputes
        # the in-window counters the controller reads.
        clock.advance(121.0)
        lim.allow("warmup")
        eff = lim.effective_limits()["attacker"]
        steps = 0
        while eff < 1000 and steps < 20:
            ctl.tick(now + 10 + steps)
            eff = lim.effective_limits()["attacker"]
            steps += 1
        assert eff == 1000  # fully recovered to the ceiling
        assert ctl.relaxed > 0
        lim.close()

    def test_tighten_vetoed_by_false_deny_bound(self):
        """A high audited false-deny Wilson bound vetoes tightening —
        the controller must not amplify the limiter's own error."""
        lim, _ = self._storm_limiter()
        audit = {"false_deny_wilson95": [0.05, 0.2]}
        ctl = AIMDController(lim, gains=self.GAINS,
                             audit_status=lambda: audit, interval=999)
        lim.allow_batch([f"a{i}" for i in range(40)] * 3)
        assert ctl.tick(0.0) == {}  # saturated + hot, but vetoed
        assert ctl.tightened == 0
        audit["false_deny_wilson95"] = [0.0, 0.001]
        assert "attacker" in ctl.tick(1.0)
        lim.close()

    def test_slo_pressure_tightens_global_without_hot_tenant(self):
        lim, _ = make(tenants=4, global_limit=100)
        slo = {"windows": {"300s": {"burn_rate": 5.0}}}
        ctl = AIMDController(lim, gains=self.GAINS,
                             slo_status=lambda: slo, interval=999)
        moved = ctl.tick(0.0)
        assert moved.get(GLOBAL) == 50
        slo["windows"]["300s"]["burn_rate"] = 0.0
        moved = ctl.tick(1.0)
        assert moved.get(GLOBAL) == 75  # 50 + 100 * 0.25
        lim.close()

    def test_idle_limiter_reports_expired_mass_as_zero(self):
        """Storm mass must not haunt an IDLE limiter: with zero traffic
        after the window rolls, hierarchy_stats re-syncs the ring
        instead of replaying the last dispatch's totals — otherwise the
        controller keeps tightening a storm that already ended and the
        relax leg never engages."""
        lim, clock = self._storm_limiter()
        ctl = AIMDController(lim, gains=self.GAINS, interval=999)
        lim.allow_batch([f"a{i}" for i in range(40)] * 3)
        assert ctl.tick(0.0).get("attacker") == 500
        # Storm ends; the window rolls with NO further decisions.
        clock.advance(121.0)
        assert lim.hierarchy_stats()["global"]["in_window"] == 0
        moved = ctl.tick(10.0)
        assert moved.get("attacker", 0) > 500   # relaxing, not tightening
        lim.close()

    def test_unlimited_ceiling_never_tightened(self):
        """A scope with no configured ceiling has no real limit to
        move: the controller must skip it (installing 0.7 x 2^40 would
        log/count a containment that contains nothing)."""
        lim, _ = make(tenants=4, global_limit=100)   # default tenant uncapped
        lim.set_tenant("victim", 1000, weight=6)
        for i in range(8):
            lim.assign_tenant(f"v{i}", "victim")
        ctl = AIMDController(lim, gains=self.GAINS, interval=999)
        # Unassigned keys flood the default (UNCAPPED) tenant past the
        # global saturation threshold; 'default' is the hot tenant.
        lim.allow_batch([f"free{i}" for i in range(95)])
        moved = ctl.tick(0.0)
        assert "default" not in moved
        assert ctl.tightened == 0
        assert lim.effective_limits()["default"] >= HIER_UNLIMITED
        lim.close()

    def test_publish_hook_fires_on_moves(self):
        lim, _ = self._storm_limiter()
        frames = []
        ctl = AIMDController(lim, gains=self.GAINS, interval=999,
                             publish=frames.append)
        lim.allow_batch([f"a{i}" for i in range(40)] * 3)
        ctl.tick(0.0)
        assert frames and frames[-1]["revision"] >= 1
        assert frames[-1]["effective"]["attacker"] == 500
        lim.close()

    def test_start_stop_thread(self):
        lim, _ = make(tenants=4, global_limit=100)
        ctl = AIMDController(lim, interval=0.01)
        ctl.start()
        ctl.start()  # idempotent
        import time as _t
        deadline = _t.monotonic() + 5.0
        while ctl.ticks == 0 and _t.monotonic() < deadline:
            _t.sleep(0.01)
        ctl.stop()
        assert ctl.ticks > 0
        lim.close()


# ------------------------------------------------------------- durability


class TestCheckpoint:
    def test_hier_state_round_trips(self, tmp_path):
        lim, _ = make(tenants=4, global_limit=100)
        lim.set_tenant("t", 50, weight=3, floor=7)
        lim.assign_tenant("k", "t")
        lim.set_effective("t", 21)            # controller-moved state
        lim.set_effective(GLOBAL, 80)
        lim.allow_batch([f"x{i}" for i in range(10)])
        path = str(tmp_path / "snap.npz")
        lim.save(path)
        lim2, _ = make(tenants=4, global_limit=100)
        lim2.restore(path)
        t = dict(lim2.list_tenants())["t"]
        assert (t.limit, t.weight, t.floor) == (50, 3, 7)
        assert lim2.tenant_of("k") == "t"
        assert lim2.effective_limits()["t"] == 21
        assert lim2.effective_limits()[GLOBAL] == 80
        # Revision restored too: the pre-snapshot frame is stale.
        assert not lim2.apply_hierarchy_payload(
            {"revision": 1, "effective": {"t": 40}})
        # In-window global mass restored with the sketch state.
        assert lim2.hierarchy_stats()["global"]["in_window"] == 10
        lim.close()
        lim2.close()

    def test_enabled_geometry_mismatch_refused(self, tmp_path):
        lim, _ = make(tenants=4)
        path = str(tmp_path / "snap.npz")
        lim.save(path)
        lim2, _ = make(tenants=8)
        with pytest.raises(CheckpointError, match="fingerprint"):
            lim2.restore(path)
        lim.close()
        lim2.close()

    def test_disabled_hierarchy_keeps_pre_adr020_fingerprint(self):
        """A disabled HierarchySpec must not change any existing
        snapshot's fingerprint (golden-pinned seed compatibility)."""
        from dataclasses import replace

        from ratelimiter_tpu.checkpoint import config_fingerprint

        cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=4,
                     window=60.0)
        same = replace(cfg, hierarchy=HierarchySpec(map_capacity=1 << 16))
        assert config_fingerprint(cfg) == config_fingerprint(same)
        enabled = replace(cfg, hierarchy=HierarchySpec(tenants=4))
        assert config_fingerprint(cfg) != config_fingerprint(enabled)


# ------------------------------------------------------------ mesh twins


class TestSlicedMesh:
    def _mesh(self, n=2, global_limit=40, **kw):
        from ratelimiter_tpu.core.config import MeshSpec

        clock = ManualClock(T0)
        cfg = Config(
            algorithm=Algorithm.SLIDING_WINDOW, limit=100_000, window=60.0,
            sketch=SketchParams(depth=3, width=1 << 14, sub_windows=4),
            mesh=MeshSpec(devices=n),
            hierarchy=HierarchySpec(tenants=4, global_limit=global_limit,
                                    **kw))
        return create_limiter(cfg, backend="mesh", clock=clock), clock

    def test_slice_share_divisor(self):
        """Each hash-routed slice enforces global_limit // n_slices; the
        deployment-wide admitted mass is the sum of slice shares."""
        mesh, _ = self._mesh(n=2, global_limit=40)
        st = mesh.hierarchy_stats()
        assert st["divisor"] == 2
        keys = [f"k{i}" for i in range(200)]
        out = mesh.allow_batch(keys)
        # Both slices see >> 20 keys, so each fills its 20-share.
        assert int(out.allowed.sum()) == 40
        assert mesh.hierarchy_stats()["global"]["in_window"] == 40
        mesh.close()

    def test_write_all_mutations_and_stats_sum(self):
        mesh, _ = self._mesh(n=2, global_limit=0)
        mesh.set_tenant("t", 30, weight=2)
        for i in range(100):
            mesh.assign_tenant(f"k{i}", "t")
        out = mesh.allow_batch([f"k{i}" for i in range(100)])
        # Tenant limit 30 → 15 per slice; both slices fill their share.
        assert int(out.allowed.sum()) == 30
        st = mesh.hierarchy_stats()
        assert st["tenants"]["t"]["in_window"] == 30
        for s in mesh.slices:
            assert s.effective_limits()["t"] == 30
        assert mesh.set_effective("t", 16) == 16
        for s in mesh.slices:
            assert s.effective_limits()["t"] == 16
        mesh.close()


class TestReplicatedMesh:
    @pytest.mark.parametrize("merge", ["gather", "delta"])
    def test_cascade_on_collective_step(self, merge):
        from ratelimiter_tpu.parallel import MeshSketchLimiter, make_mesh

        clock = ManualClock(T0)
        cfg = Config(
            algorithm=Algorithm.SLIDING_WINDOW, limit=100_000, window=60.0,
            sketch=SketchParams(depth=3, width=1 << 14, sub_windows=4),
            hierarchy=HierarchySpec(tenants=4, global_limit=10))
        lim = MeshSketchLimiter(cfg, clock, mesh=make_mesh(n_devices=8),
                                merge=merge)
        out = lim.allow_batch([f"k{i}" for i in range(32)])
        st = lim.hierarchy_stats()
        if merge == "gather":
            # Gather mode decides globally: exactly the global limit.
            assert int(out.allowed.sum()) == 10
        # Either mode: the psum'd counter slab agrees with the verdicts
        # (delta admits per-chip against bounded-stale counters, so the
        # total may overshoot within the first batch — but accounting
        # must match what was actually admitted).
        assert st["global"]["in_window"] == int(out.allowed.sum())
        # Once counters reflect saturation, later batches deny.
        out2 = lim.allow_batch([f"m{i}" for i in range(32)])
        assert int(out2.allowed.sum()) == 0
        lim.close()


# -------------------------------------------------------------- fanout


class TestFanout:
    def test_write_all_read_one_sum_stats(self):
        a, _ = make(tenants=4, global_limit=100)
        b, _ = make(tenants=4, global_limit=100)
        fan = HierarchyFanout([a, b])
        fan.set_tenant("t", 40, weight=2)
        fan.assign_tenant("k", "t")
        assert fan.tenant_of("k") == "t"
        assert fan.set_effective("t", 20) == 20
        assert a.effective_limits()["t"] == 20
        assert b.effective_limits()["t"] == 20
        a.allow("k")
        b.allow("k")
        b.allow("k")
        st = fan.hierarchy_stats()
        assert st["tenants"]["t"]["in_window"] == 3
        assert st["global"]["in_window"] == 3
        assert fan.apply_hierarchy_payload(
            {"revision": 9, "effective": {"t": 25}})
        assert b.effective_limits()["t"] == 25
        with pytest.raises(ValueError):
            HierarchyFanout([])
        a.close()
        b.close()


# ------------------------------------------------------- table unit tests


class TestTenantTableDirect:
    def _table(self, divisor=1, tenants=4, global_limit=100):
        cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=10,
                     window=60.0,
                     hierarchy=HierarchySpec(tenants=tenants,
                                             global_limit=global_limit))
        return TenantTable(cfg, key_fn=lambda k: hash(k) or 1,
                           divisor=divisor)

    def test_host_arrays_sorted_and_divided(self):
        t = self._table(divisor=4)
        t.set_tenant("t", 40)
        for i in range(5):
            t.assign(f"k{i}", "t")
        arrs = t.host_arrays()
        keys = arrs["key"][:5]
        assert list(keys) == sorted(keys)
        tid = t.get_tenant("t").tid
        assert arrs["limit"][tid] == 10      # 40 // divisor 4
        assert arrs["limit"][4] == 25        # global 100 // 4
        assert arrs["limit"][2] == HIER_UNLIMITED  # unregistered slot
        t2 = self._table(divisor=64, global_limit=10)
        assert t2.host_arrays()["limit"][4] == 1  # share floors at 1

    def test_needs_enabled_spec(self):
        cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=10,
                     window=60.0)
        with pytest.raises(InvalidConfigError):
            TenantTable(cfg, key_fn=hash)
