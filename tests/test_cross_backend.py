"""Property test: the dense device backend agrees bit-for-bit with the exact
oracle on randomized traces (keys, n, virtual-time jumps, window rolls).

This is the framework's analog of the reference testing the same go-redis
code path against miniredis (SURVEY.md §4.2.1): two independent
implementations of the same integer recurrences must never disagree.
"""

import numpy as np
import pytest

from ratelimiter_tpu import Algorithm, Config, DenseParams, ManualClock, create_limiter

ALGOS = [Algorithm.TOKEN_BUCKET, Algorithm.SLIDING_WINDOW, Algorithm.FIXED_WINDOW]


@pytest.mark.parametrize("algo", ALGOS, ids=str)
@pytest.mark.parametrize("seed", range(4))
def test_dense_matches_oracle_scalar_trace(algo, seed):
    rng = np.random.default_rng(seed)
    cfg = Config(algorithm=algo, limit=int(rng.integers(3, 30)),
                 window=float(rng.choice([1.0, 7.5, 60.0])),
                 dense=DenseParams(capacity=16))
    ce, cd = ManualClock(1_700_000_000.0), ManualClock(1_700_000_000.0)
    exact = create_limiter(cfg, backend="exact", clock=ce)
    dense = create_limiter(cfg, backend="dense", clock=cd)
    keys = [f"user:{i}" for i in range(6)]
    for step in range(200):
        dt = float(rng.exponential(cfg.window / 20))
        ce.advance(dt)
        cd.advance(dt)
        key = keys[int(rng.integers(0, len(keys)))]
        n = int(rng.integers(1, 4))
        re = exact.allow_n(key, n)
        rd = dense.allow_n(key, n)
        assert re.allowed == rd.allowed, f"step {step}: {re} vs {rd}"
        assert re.remaining == rd.remaining, f"step {step}: {re} vs {rd}"
        assert re.retry_after == pytest.approx(rd.retry_after, abs=2e-6), f"step {step}"
        assert re.reset_at == pytest.approx(rd.reset_at, abs=2e-6), f"step {step}"
    exact.close()
    dense.close()


@pytest.mark.parametrize("algo", ALGOS, ids=str)
@pytest.mark.parametrize("seed", range(3))
def test_dense_matches_oracle_batched_trace(algo, seed):
    """Batched dispatches with duplicate keys vs the oracle's sequential
    semantics — the serialized-Lua equivalence (SURVEY.md §7.4.1).
    Uniform n=1 per batch keeps the greedy fixpoint provably exact."""
    rng = np.random.default_rng(1000 + seed)
    cfg = Config(algorithm=algo, limit=25, window=10.0,
                 dense=DenseParams(capacity=32))
    ce, cd = ManualClock(1_700_000_000.0), ManualClock(1_700_000_000.0)
    exact = create_limiter(cfg, backend="exact", clock=ce)
    dense = create_limiter(cfg, backend="dense", clock=cd)
    for step in range(30):
        dt = float(rng.exponential(1.0))
        ce.advance(dt)
        cd.advance(dt)
        B = int(rng.integers(1, 40))
        keys = [f"u{rng.integers(0, 5)}" for _ in range(B)]
        out_d = dense.allow_batch(keys)
        out_e = exact.allow_batch(keys)
        np.testing.assert_array_equal(out_d.allowed, out_e.allowed, err_msg=f"step {step}")
        np.testing.assert_array_equal(out_d.remaining, out_e.remaining, err_msg=f"step {step}")
    exact.close()
    dense.close()
