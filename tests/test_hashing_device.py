"""Host/device hashing equivalence — fuzz-pinned bit-exactness (ADR-011).

The hashed hot path now splits every batch's u64 hashes into (h1, h2)
INSIDE the jitted step (ops/hashing.split_hash_dev), and the raw-id wire
lane finalizes with splitmix64 either on device (asyncio door,
premix=True) or in C++ (native door io threads). Four implementations of
the same two functions therefore coexist — host NumPy, device jnp, C++
(server.cpp), and whatever hash_strings_u64 feeds them — and ANY drift
re-keys every sketch silently. This suite fuzzes random unicode keys and
raw ids through every pairing and requires bit-exact agreement.
"""

from __future__ import annotations

import numpy as np
import pytest

from ratelimiter_tpu.ops.hashing import (
    hash_strings_u64,
    split_hash,
    split_hash_dev,
    splitmix64,
    splitmix64_dev,
)

SEEDS = [0, 1, 0x5BD1E995, 0xFFFFFFFF]


def _random_unicode_keys(rng, n):
    pools = [
        lambda: "".join(chr(rng.integers(0x20, 0x7F)) for _ in range(
            rng.integers(1, 24))),
        lambda: "".join(chr(rng.integers(0x80, 0x800)) for _ in range(
            rng.integers(1, 12))),
        lambda: "".join(chr(rng.integers(0x4E00, 0x9FFF)) for _ in range(
            rng.integers(1, 8))),
        lambda: "🔑" * int(rng.integers(1, 5)) + str(rng.integers(1 << 30)),
    ]
    return [pools[int(rng.integers(len(pools)))]() for _ in range(n)]


@pytest.fixture(scope="module")
def jit_twins():
    import jax
    import jax.numpy as jnp

    mix = jax.jit(splitmix64_dev)

    def split(seed):
        @jax.jit
        def f(h):
            return split_hash_dev(h, seed)

        return f

    return mix, split, jnp


def test_splitmix64_host_device_bit_exact(jit_twins):
    mix, _, jnp = jit_twins
    rng = np.random.default_rng(7)
    ids = np.concatenate([
        rng.integers(0, 1 << 63, size=512, dtype=np.uint64),
        np.array([0, 1, (1 << 64) - 1, 0x9E3779B97F4A7C15], np.uint64),
    ])
    np.testing.assert_array_equal(np.asarray(mix(jnp.asarray(ids))),
                                  splitmix64(ids))


@pytest.mark.parametrize("seed", SEEDS)
def test_split_hash_host_device_bit_exact(jit_twins, seed):
    _, split, jnp = jit_twins
    rng = np.random.default_rng(seed + 11)
    h64 = rng.integers(0, 1 << 63, size=512, dtype=np.uint64) * np.uint64(3)
    want1, want2 = split_hash(h64, seed)
    got1, got2 = split(seed)(jnp.asarray(h64))
    np.testing.assert_array_equal(np.asarray(got1), want1)
    np.testing.assert_array_equal(np.asarray(got2), want2)
    assert (np.asarray(got2) & 1).all()  # h2 odd: full-width strides


@pytest.mark.parametrize("seed", [0, 0x5BD1E995])
def test_unicode_keys_end_to_end(jit_twins, seed):
    """String keys -> native/fallback bulk hash -> host split vs device
    split: the exact path a sketch decision takes, fuzzz over unicode."""
    _, split, jnp = jit_twins
    rng = np.random.default_rng(23)
    keys = _random_unicode_keys(rng, 256)
    h64 = hash_strings_u64(keys)
    want1, want2 = split_hash(h64, seed)
    got1, got2 = split(seed)(jnp.asarray(h64))
    np.testing.assert_array_equal(np.asarray(got1), want1)
    np.testing.assert_array_equal(np.asarray(got2), want2)


def test_native_hasher_agrees_with_fallback_on_unicode():
    """hash_strings_u64 (C++ when available) vs the NumPy twin, over the
    same fuzzed unicode keys — the native half of the wire contract."""
    from ratelimiter_tpu.native import hash_packed, pack_keys
    from ratelimiter_tpu.native.fallback import hash_packed_numpy

    rng = np.random.default_rng(31)
    keys = _random_unicode_keys(rng, 256)
    buf, offsets, lengths = pack_keys(keys)
    np.testing.assert_array_equal(
        hash_packed(buf, offsets, lengths),
        hash_packed_numpy(buf, offsets, lengths,
                          __import__("ratelimiter_tpu.native",
                                     fromlist=["DEFAULT_SEED"]).DEFAULT_SEED))


def test_cpp_door_splitmix_matches_host():
    """The C++ door finalizes raw wire ids with its own splitmix64
    (server.cpp). Scalar transcription of that code must equal the NumPy
    host (and by the tests above, the device) implementation."""
    M64 = (1 << 64) - 1

    def cpp_splitmix64(x: int) -> int:  # server.cpp, line for line
        x = (x + 0x9E3779B97F4A7C15) & M64
        x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & M64
        x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & M64
        return x ^ (x >> 31)

    rng = np.random.default_rng(41)
    ids = np.concatenate([
        rng.integers(0, 1 << 63, size=256, dtype=np.uint64),
        np.array([0, 1, (1 << 64) - 1], np.uint64),
    ])
    want = splitmix64(ids)
    for raw, w in zip(ids.tolist(), want.tolist()):
        assert cpp_splitmix64(raw) == w


def test_raw_id_lane_equals_prefinalized_lane():
    """allow_ids(raw) (device-side splitmix64+split) must decide exactly
    like allow_hashed(splitmix64(raw)) (host finalize, device split) —
    the asyncio door and the C++ door feed the same sketch cells."""
    from ratelimiter_tpu import Algorithm, Config, SketchParams
    from ratelimiter_tpu.algorithms.sketch import SketchLimiter
    from ratelimiter_tpu.core.clock import ManualClock

    cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=4, window=6.0,
                 sketch=SketchParams(depth=3, width=128, sub_windows=6))
    a = SketchLimiter(cfg, ManualClock(1_000_000.0))
    b = SketchLimiter(cfg, ManualClock(1_000_000.0))
    try:
        rng = np.random.default_rng(3)
        for _ in range(8):
            ids = rng.integers(1, 40, size=64).astype(np.uint64)
            ra = a.allow_ids(ids)
            rb = b.allow_hashed(splitmix64(ids))
            np.testing.assert_array_equal(ra.allowed, rb.allowed)
            np.testing.assert_array_equal(ra.remaining, rb.remaining)
            np.testing.assert_array_equal(ra.retry_after, rb.retry_after)
            np.testing.assert_array_equal(ra.reset_at, rb.reset_at)
            a.clock.advance(0.7)
            b.clock.advance(0.7)
    finally:
        a.close()
        b.close()
