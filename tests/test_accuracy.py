"""Sketch accuracy vs the exact oracle (CI-scale version of the BASELINE
metric: false-positive-deny rate on a Zipf trace must stay within budget;
over-admission vs the sketch's own semantics must be zero)."""

import pytest

from ratelimiter_tpu.core.config import SketchParams
from ratelimiter_tpu.evaluation import evaluate_accuracy


@pytest.mark.slow
def test_false_deny_rate_within_budget():
    rep = evaluate_accuracy(
        n_keys=5000, n_requests=20000, batch=1024, limit=50, window=60.0,
        request_rate=10000.0,
        sketch=SketchParams(depth=4, width=8192, sub_windows=60))
    # BASELINE budget is 1% at full scale; CI scale keeps a margin.
    assert rep.false_deny_rate <= 0.01, rep.as_dict()
    # CMS-only error (vs the collision-free twin) within the same budget.
    assert rep.cms_false_deny_rate <= 0.01, rep.as_dict()


@pytest.mark.slow
def test_undersized_sketch_fails_toward_denial():
    """A deliberately tiny sketch must degrade by denying more, never by
    over-admitting (the availability-vs-correctness direction the design
    guarantees — ops/sketch_kernels.py docstring)."""
    rep = evaluate_accuracy(
        n_keys=2000, n_requests=8000, batch=512, limit=20, window=60.0,
        request_rate=4000.0, include_twin=True,
        sketch=SketchParams(depth=2, width=256, sub_windows=30))
    assert rep.false_deny_rate > 0.0  # collisions actually bite here
    # Any false allows can come only from sub-window vs two-window semantics,
    # not from the sketch (which only overestimates).
    assert rep.false_allows_vs_oracle <= rep.semantic_disagreements
