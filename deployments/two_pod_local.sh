#!/usr/bin/env bash
# Runnable two-pod deployment: two NATIVE rate-limit servers exchanging
# cross-pod history over HMAC-tagged DCN pushes, each fronting binary +
# HTTP (add --grpc-port to COMMON for the gRPC surface). This is the
# process-level shape the docker-compose.yml / systemd units in this
# directory describe declaratively — same flags, same topology — and it
# is smoke-tested in CI (tests/test_deployments.py).
#
# Usage: deployments/two_pod_local.sh [seconds_to_stay_up]
# Env:   RATELIMITER_TPU_DCN_SECRET   shared push secret (default demo)
#        PORT_A/PORT_B/HTTP_A/HTTP_B  fixed ports (default: ephemeral)
set -euo pipefail

REPO="$(cd "$(dirname "$0")/.." && pwd)"
export PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}"
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export RATELIMITER_TPU_DCN_SECRET="${RATELIMITER_TPU_DCN_SECRET:-demo-secret}"
STAY_UP="${1:-15}"

pick_port() {
  python - <<'EOF'
import socket
s = socket.socket(); s.bind(("127.0.0.1", 0)); print(s.getsockname()[1]); s.close()
EOF
}
PORT_A="${PORT_A:-$(pick_port)}"
PORT_B="${PORT_B:-$(pick_port)}"
HTTP_A="${HTTP_A:-$(pick_port)}"
HTTP_B="${HTTP_B:-$(pick_port)}"

COMMON=(python -m ratelimiter_tpu.serving
        --backend sketch --algorithm sliding_window
        --limit 100 --window 60
        --sketch-depth 4 --sketch-width 65536
        --native --shards 2 --dcn-interval 1.0
        --http-reset-token "${HTTP_RESET_TOKEN:-admin-token}")
# PREWARM=0: skip jit pre-warming (smoke tests / cold caches); production
# keeps it so no client request ever pays a compile.
if [ "${PREWARM:-1}" = "0" ]; then COMMON+=(--no-prewarm); fi

"${COMMON[@]}" --port "$PORT_A" --http-port "$HTTP_A" \
    --dcn-peer "127.0.0.1:$PORT_B" &
PID_A=$!
"${COMMON[@]}" --port "$PORT_B" --http-port "$HTTP_B" \
    --dcn-peer "127.0.0.1:$PORT_A" &
PID_B=$!
trap 'kill -TERM $PID_A $PID_B 2>/dev/null; wait $PID_A $PID_B 2>/dev/null' EXIT
trap 'exit 0' TERM INT   # graceful stop (the EXIT trap drains the pods)

# Wait for both HTTP gateways to answer.
for port in "$HTTP_A" "$HTTP_B"; do
  for _ in $(seq 1 120); do
    if curl -fsS "http://127.0.0.1:$port/healthz" >/dev/null 2>&1; then
      break
    fi
    sleep 0.5
  done
done

echo "pod A: binary 127.0.0.1:$PORT_A  http 127.0.0.1:$HTTP_A"
echo "pod B: binary 127.0.0.1:$PORT_B  http 127.0.0.1:$HTTP_B"
echo "try:   curl 'http://127.0.0.1:$HTTP_A/v1/allow?key=user:42'"
echo "       curl 'http://127.0.0.1:$HTTP_B/v1/allow?key=user:42'  # shared quota within ~2 DCN cycles"
echo "       curl 'http://127.0.0.1:$HTTP_A/healthz'"
echo "up for ${STAY_UP}s (SIGTERM both pods on exit)"
# Background sleep + wait: bash only runs signal traps once the current
# foreground command finishes, so a plain sleep would stall SIGTERM for
# the whole STAY_UP.
sleep "$STAY_UP" &
wait $! || true
