#!/usr/bin/env python
"""Fleet status rollup CLI (ADR-021): one merged view of the whole
fleet's observability — merged audit Wilson bounds, fleet-wide top-K
consumers, pooled SLO burn, per-scope hierarchy mass, liveness and
epochs — from any member.

    python tools/fleet_status.py http://member:8434
    python tools/fleet_status.py http://member:8434 --json
    python tools/fleet_status.py http://member:8434 --offline

Default mode asks the member to fan out (``GET /v1/fleet/status`` —
the member pulls every peer's /healthz over the fleet map's declared
gateway ports and merges with ratelimiter_tpu.fleet.tower). ``--offline``
pulls each member's /healthz from THIS box and merges locally with the
same code — for when the members cannot reach each other's gateways.
"""

from __future__ import annotations

import argparse
import json
import sys


def _fail(msg: str) -> None:
    print(f"error: {msg}", file=sys.stderr)
    raise SystemExit(1)


def rollup_via_member(base: str, timeout: float) -> dict:
    from ratelimiter_tpu.fleet.tower import fetch_json

    return fetch_json(base.rstrip("/") + "/v1/fleet/status",
                      timeout=timeout)


def rollup_offline(base: str, timeout: float) -> dict:
    from ratelimiter_tpu.fleet.tower import fetch_json, merged_status

    base = base.rstrip("/")
    health = fetch_json(base + "/healthz", timeout=timeout)
    fleet = health.get("fleet")
    if not fleet:
        _fail("--offline needs a fleet member (no fleet block on "
              "/healthz)")
    ref = fleet["self"]
    members = {ref: health}
    for peer_id, entry in (fleet.get("hosts") or {}).items():
        if peer_id == ref:
            continue
        http = entry.get("http")
        if not http:
            members[peer_id] = None
            continue
        host = entry.get("addr", "").rsplit(":", 1)[0]
        try:
            members[peer_id] = fetch_json(
                f"http://{host}:{http}/healthz", timeout=timeout)
        except Exception as exc:  # noqa: BLE001 — named gap
            print(f"warning: {peer_id} unreachable ({exc})",
                  file=sys.stderr)
            members[peer_id] = None
    out = merged_status(members)
    out["generated_by"] = f"offline merge via {ref}"
    return out


def _render(st: dict) -> None:
    print(f"fleet: {st.get('reachable')}/{st.get('members')} members "
          f"reachable, epoch {st.get('epoch')}"
          f"{'' if st.get('epoch_converged') else '  [EPOCH SPLIT]'}, "
          f"{st.get('decisions_total'):,} decisions")
    for host, d in sorted((st.get("hosts") or {}).items()):
        if not d.get("reachable"):
            print(f"  {host}: UNREACHABLE")
            continue
        mem = d.get("member") or {}
        print(f"  {host}: epoch={d.get('epoch')} "
              f"decisions={d.get('decisions_total'):,} "
              f"forwarded={d.get('forwarded_total')} "
              f"door={mem.get('door')} backend={mem.get('backend')}")
    audit = st.get("audit")
    if audit:
        lo, hi = audit["false_deny_wilson95"]
        print(f"audit (merged over {audit['samples']:,} samples): "
              f"false-deny {audit['false_deny_rate']:.5f} "
              f"wilson95 [{lo:.5f}, {hi:.5f}], "
              f"false-allow {audit['false_allow_rate']:.2e}")
    slo = st.get("slo")
    if slo:
        for wname, row in sorted(slo.get("windows", {}).items()):
            print(f"slo {wname}: burn {row['burn_rate']} "
                  f"(latency {row['latency_bad_fraction']}, "
                  f"availability {row['availability_bad_fraction']}) "
                  f"per-host {row.get('per_host_burn')}")
    cons = st.get("consumers")
    if cons and cons.get("top"):
        print(f"top consumers (fleet-merged, {cons['tracked_mass']:,} "
              f"tracked mass):")
        for i, row in enumerate(cons["top"][:10], 1):
            print(f"  #{i} {row['consumer']} in_window="
                  f"{row['in_window']:,} share={row['share']} "
                  f"hosts={sorted(row['hosts'])}")
    hier = st.get("hierarchy")
    if hier:
        g = hier.get("global") or {}
        print(f"hierarchy: global in_window={g.get('in_window')} "
              f"effective={g.get('effective')}")
        for name, t in sorted((hier.get("tenants") or {}).items()):
            print(f"  tenant {name}: in_window={t.get('in_window')} "
                  f"effective={t.get('effective')} "
                  f"per-host {t.get('per_host_in_window')}")


def main() -> None:
    ap = argparse.ArgumentParser(
        description="Merged fleet observability rollup (ADR-021)")
    ap.add_argument("gateway", help="any member's HTTP gateway, e.g. "
                                    "http://host:8434")
    ap.add_argument("--offline", action="store_true",
                    help="pull each member's /healthz from this box "
                         "and merge locally (same merge code)")
    ap.add_argument("--json", action="store_true",
                    help="print the full merged JSON instead of the "
                         "summary")
    ap.add_argument("--timeout", type=float, default=10.0)
    args = ap.parse_args()
    try:
        st = (rollup_offline(args.gateway, args.timeout) if args.offline
              else rollup_via_member(args.gateway, args.timeout))
    except Exception as exc:  # noqa: BLE001
        _fail(str(exc))
    if args.json:
        json.dump(st, sys.stdout, indent=2)
        print()
    else:
        _render(st)


if __name__ == "__main__":
    main()
