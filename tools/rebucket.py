#!/usr/bin/env python
"""Offline snapshot re-bucketing: resize a checkpoint onto a new slice
count without booting a server (the cold half of ADR-018's elastic
resharding; the live half is ``SlicedMeshLimiter.restore``).

    python tools/rebucket.py IN.npz OUT.npz --slices M

Accepts both snapshot shapes:

* a combined mesh snapshot (kind ``mesh:<kind>``, ``slice{i}:`` arrays) —
  re-bucketed onto M slices (M == 1 emits a plain single-unit snapshot);
* a plain single-unit snapshot (kind ``sketch`` — the PR 2 durability
  format) — treated as a 1-slice mesh; M == 1 round-trips it unchanged,
  M > 1 splits it into a combined ``mesh:`` snapshot.

The config fingerprint is carried through verbatim: re-bucketing changes
WHERE state lives (the ``mesh`` spec is excluded from the fingerprint,
checkpoint.py), never what it means — the output restores under the same
flags plus the new ``--mesh-devices``.

Pure host numpy; no JAX, no device, no running server required.
"""

from __future__ import annotations

import argparse
import io
import json
import os
import sys

import numpy as np

# Runnable straight from a checkout: python tools/rebucket.py ...
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_META_KEY = "__ratelimiter_tpu_meta__"  # checkpoint._META_KEY


def load_raw(path: str):
    with np.load(path, allow_pickle=False) as z:
        if _META_KEY not in z.files:
            raise SystemExit(f"{path}: not a ratelimiter_tpu checkpoint")
        arrays = {k: z[k] for k in z.files if k != _META_KEY}
        meta = json.loads(bytes(z[_META_KEY]).decode())
    return arrays, meta


def save_raw(path: str, arrays: dict, meta: dict) -> None:
    from ratelimiter_tpu.checkpoint import write_atomic

    buf = io.BytesIO()
    np.savez(buf, **arrays,
             **{_META_KEY: np.frombuffer(
                 json.dumps(meta).encode(), dtype=np.uint8)})
    write_atomic(path, buf.getvalue())


def rebucket_file(src: str, dst: str, new_n: int) -> dict:
    from ratelimiter_tpu.parallel import reshard

    arrays, meta = load_raw(src)
    kind = str(meta.get("kind", ""))
    if kind.startswith("mesh:"):
        states, extras = reshard.split_combined(arrays, meta)
        base_kind = kind[len("mesh:"):]
    else:
        # Plain single-unit snapshot == a 1-slice mesh.
        states, extras = [dict(arrays)], [
            {k: meta[k] for k in ("saved_at", "host_period")
             if k in meta}]
        base_kind = kind
    old_n = len(states)
    new_states, new_extras = reshard.rebucket(states, extras, new_n)
    out_meta = dict(meta)
    out_meta["rebucketed_from"] = old_n
    if new_n == 1:
        out_arrays = new_states[0]
        out_meta["kind"] = base_kind
        out_meta.pop("n_slices", None)
        out_meta.pop("slice_extras", None)
        out_meta.update(new_extras[0])
    else:
        out_arrays, out_meta = reshard.join_combined(
            new_states, new_extras, out_meta)
        out_meta["kind"] = f"mesh:{base_kind}"
    save_raw(dst, out_arrays, out_meta)
    return {"old_slices": old_n, "new_slices": new_n,
            "kind": out_meta["kind"], "arrays": len(out_arrays)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tools/rebucket.py",
        description="resize a ratelimiter_tpu snapshot onto a new "
                    "slice count (offline elastic resharding, ADR-018)")
    ap.add_argument("src", help="input snapshot (.npz)")
    ap.add_argument("dst", help="output snapshot (.npz)")
    ap.add_argument("--slices", type=int, required=True,
                    help="target slice count (>= 1)")
    args = ap.parse_args(argv)
    if args.slices < 1:
        ap.error("--slices must be >= 1")
    info = rebucket_file(args.src, args.dst, args.slices)
    print(f"rebucketed {args.src} ({info['old_slices']} slice(s)) -> "
          f"{args.dst} ({info['new_slices']} slice(s), "
          f"kind={info['kind']})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
