#!/usr/bin/env python
"""Operator CLI for live range migration (ADR-018's residual operator
surface): a thin wrapper over the bearer-gated gateway endpoint

    POST /v1/fleet/migrate?to=HOST:PORT&ranges=lo:hi[,lo:hi...]&wait=S

so a live rebalance stops requiring a library call into
``FleetMembership.migrate_ranges``.

    python tools/fleet_migrate.py http://donor-host:8433 \
        --to receiver-host:9433 --ranges 48:64 --token $MIGRATE_TOKEN

The gateway must have been started with ``--http-migrate-token`` on a
fleet member (there is no tokenless migrate surface). The donor performs
the capture → WAL-suffix replay → epoch-flip handoff (ADR-018) and the
command returns the post-move epoch on success, or the donor's error
with a non-zero exit code.

Pure stdlib (urllib); no client library import, so it runs from any
operator box that can reach the gateway port.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.parse
import urllib.request


def parse_ranges(raw: str):
    """Validate lo:hi[,lo:hi...] client-side so typos fail before the
    donor starts a capture."""
    out = []
    for part in raw.split(","):
        try:
            lo, hi = part.split(":")
            lo_i, hi_i = int(lo), int(hi)
        except ValueError:
            raise SystemExit(f"bad range {part!r}; expected lo:hi")
        if lo_i >= hi_i:
            raise SystemExit(f"empty range {part!r} (lo must be < hi)")
        out.append((lo_i, hi_i))
    return out


def migrate(gateway: str, *, to: str, ranges: str, wait: float,
            token: str, timeout: float) -> dict:
    q = urllib.parse.urlencode(
        {"to": to, "ranges": ranges, "wait": wait})
    url = f"{gateway.rstrip('/')}/v1/fleet/migrate?{q}"
    req = urllib.request.Request(
        url, method="POST",
        headers={"Authorization": f"Bearer {token}"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read().decode())
    except urllib.error.HTTPError as exc:
        # The gateway answers errors as JSON too (403/400/504); surface
        # its body, not a bare traceback.
        try:
            body = json.loads(exc.read().decode())
        except Exception:  # noqa: BLE001 — non-JSON error page
            body = {"error": str(exc)}
        body.setdefault("ok", False)
        body["http_status"] = exc.code
        return body


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Live range migration via a fleet member's HTTP "
                    "gateway (POST /v1/fleet/migrate).")
    ap.add_argument("gateway",
                    help="donor's gateway base URL, e.g. http://host:8433")
    ap.add_argument("--to", required=True,
                    help="receiver fleet address host:port")
    ap.add_argument("--ranges", required=True,
                    help="bucket ranges to move: lo:hi[,lo:hi...]")
    ap.add_argument("--wait", type=float, default=10.0,
                    help="seconds the donor waits for the handoff flip "
                         "(default 10)")
    ap.add_argument("--token", required=True,
                    help="bearer token (the server's --http-migrate-token)")
    ap.add_argument("--timeout", type=float, default=None,
                    help="HTTP timeout (default: wait + 15s)")
    args = ap.parse_args(argv)

    parse_ranges(args.ranges)
    out = migrate(args.gateway, to=args.to, ranges=args.ranges,
                  wait=args.wait, token=args.token,
                  timeout=args.timeout or args.wait + 15.0)
    print(json.dumps(out, indent=2, sort_keys=True))
    return 0 if out.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
