#!/usr/bin/env python
"""Operator CLI for the placement brain (ADR-023): a thin wrapper over
the bearer-gated gateway endpoint

    GET  /v1/fleet/rebalance            -> controller status
    POST /v1/fleet/rebalance?action=dry-run | apply | abort

    python tools/fleet_rebalance.py http://member:8433 status \
        --token $REBALANCE_TOKEN
    python tools/fleet_rebalance.py http://member:8433 dry-run \
        --token $REBALANCE_TOKEN
    python tools/fleet_rebalance.py http://member:8433 apply \
        --token $REBALANCE_TOKEN

The gateway must have been started with ``--http-rebalance-token`` on a
fleet member (there is no tokenless rebalance surface). ``dry-run``
returns the plan the member would execute right now without moving
anything; ``apply`` clears any operator hold and runs one full cycle
synchronously; ``abort`` stops the in-flight plan between moves and
holds the background loop until the next ``apply``.

Pure stdlib (urllib); no client library import, so it runs from any
operator box that can reach the gateway port.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.parse
import urllib.request

ACTIONS = ("status", "dry-run", "apply", "abort")


def rebalance(gateway: str, action: str, *, token: str,
              timeout: float) -> dict:
    base = f"{gateway.rstrip('/')}/v1/fleet/rebalance"
    if action == "status":
        url, method = base, "GET"
    else:
        q = urllib.parse.urlencode({"action": action})
        url, method = f"{base}?{q}", "POST"
    req = urllib.request.Request(
        url, method=method,
        headers={"Authorization": f"Bearer {token}"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read().decode())
    except urllib.error.HTTPError as exc:
        # The gateway answers errors as JSON too (403/400/409); surface
        # its body, not a bare traceback.
        try:
            body = json.loads(exc.read().decode())
        except Exception:  # noqa: BLE001 — non-JSON error page
            body = {"error": str(exc)}
        body.setdefault("ok", False)
        body["http_status"] = exc.code
        return body


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Fleet rebalance control via a member's HTTP "
                    "gateway (/v1/fleet/rebalance).")
    ap.add_argument("gateway",
                    help="member's gateway base URL, e.g. http://host:8433")
    ap.add_argument("action", choices=ACTIONS,
                    help="status: controller state; dry-run: plan without "
                         "moving; apply: run one cycle now (clears a hold); "
                         "abort: stop between moves and hold the loop")
    ap.add_argument("--token", required=True,
                    help="bearer token (the server's "
                         "--http-rebalance-token)")
    ap.add_argument("--timeout", type=float, default=120.0,
                    help="HTTP timeout; apply blocks for the full cycle "
                         "(default 120)")
    args = ap.parse_args(argv)

    out = rebalance(args.gateway, args.action, token=args.token,
                    timeout=args.timeout)
    print(json.dumps(out, indent=2, sort_keys=True))
    return 0 if out.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
