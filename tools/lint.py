#!/usr/bin/env python
"""Self-contained linter (the .golangci.yml analog for an image with no
ruff/flake8 installed; pyproject.toml carries the ruff config for
environments that have it).

Checks, in the spirit of the reference's errcheck/govet/unused set:
  syntax        every file parses (ast)
  unused-import module-level imports never referenced
  tabs          no tab indentation
  trailing-ws   no trailing whitespace
  long-lines    > 100 columns (warn only)
  bare-except   `except:` without an exception class
  debug-print   print() in library code (CLIs/benchmarks exempt)

Exit status 1 on any error-level finding. Usage: python tools/lint.py
"""

from __future__ import annotations

import ast
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT_DIRS = ("ratelimiter_tpu", "tests", "benchmarks", "tools")
#: print() is the UI in these (CLI entry points, benches, test harness).
PRINT_OK = {"ratelimiter_tpu/serving/__main__.py", "benchmarks",
            "tools", "tests", "bench.py", "__graft_entry__.py"}


def _print_allowed(rel: str) -> bool:
    return any(rel == p or rel.startswith(p.rstrip("/") + "/")
               or rel.startswith(p) for p in PRINT_OK)


class _ImportVisitor(ast.NodeVisitor):
    def __init__(self):
        self.imports: dict[str, int] = {}   # name -> lineno
        self.used: set[str] = set()

    def visit_Import(self, node):
        for a in node.names:
            name = (a.asname or a.name).split(".")[0]
            self.imports[name] = node.lineno

    def visit_ImportFrom(self, node):
        if node.module == "__future__":
            return  # compiler directives, not bindings
        for a in node.names:
            if a.name == "*":
                continue
            self.imports[a.asname or a.name] = node.lineno

    def visit_Name(self, node):
        self.used.add(node.id)

    def visit_Attribute(self, node):
        self.generic_visit(node)


def lint_file(path: str, rel: str) -> list[tuple[str, int, str]]:
    errs: list[tuple[str, int, str]] = []
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=rel)
    except SyntaxError as e:
        return [("syntax", e.lineno or 0, str(e.msg))]

    for i, line in enumerate(src.splitlines(), 1):
        if line.rstrip("\n") != line.rstrip():
            errs.append(("trailing-ws", i, "trailing whitespace"))
        if line.startswith("\t"):
            errs.append(("tabs", i, "tab indentation"))

    # Unused module-level imports (conservative: any Name/attr use or
    # __all__ mention counts; noqa comment suppresses).
    lines = src.splitlines()
    v = _ImportVisitor()
    for node in tree.body:
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            v.visit(node)
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            v.used.add(node.id)
        elif isinstance(node, ast.Attribute):
            pass
    exported = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    for elt in getattr(node.value, "elts", []):
                        if isinstance(elt, ast.Constant):
                            exported.add(str(elt.value))
    for name, lineno in v.imports.items():
        line = lines[lineno - 1] if lineno - 1 < len(lines) else ""
        if name not in v.used and name not in exported \
                and "noqa" not in line and not name.startswith("_"):
            errs.append(("unused-import", lineno, f"'{name}' imported but unused"))

    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler) and node.type is None:
            errs.append(("bare-except", node.lineno, "bare 'except:'"))
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "print" and not _print_allowed(rel)):
            errs.append(("debug-print", node.lineno,
                         "print() in library code"))
    return errs


def main() -> int:
    failures = 0
    warnings = 0
    targets = []
    for d in LINT_DIRS:
        root = os.path.join(REPO, d)
        if os.path.isfile(root):
            targets.append(root)
            continue
        for dirpath, _dirnames, filenames in os.walk(root):
            if "__pycache__" in dirpath:
                continue
            targets.extend(os.path.join(dirpath, f)
                           for f in filenames if f.endswith(".py"))
    targets.extend(os.path.join(REPO, f)
                   for f in ("bench.py", "__graft_entry__.py"))
    for path in sorted(targets):
        rel = os.path.relpath(path, REPO)
        for kind, lineno, msg in lint_file(path, rel):
            if kind == "long-lines":
                warnings += 1
            else:
                failures += 1
            print(f"{rel}:{lineno}: [{kind}] {msg}")
    # Long lines: warn only (readability, not correctness).
    for path in sorted(targets):
        rel = os.path.relpath(path, REPO)
        with open(path, encoding="utf-8") as f:
            for i, line in enumerate(f, 1):
                if len(line.rstrip("\n")) > 100:
                    print(f"{rel}:{i}: [long-line] {len(line.rstrip())} cols (warn)")
                    warnings += 1
    if failures:
        print(f"lint: {failures} error(s), {warnings} warning(s)")
        return 1
    print(f"lint: clean ({len(targets)} files, {warnings} warning(s))")
    return 0


if __name__ == "__main__":
    sys.exit(main())
