#!/usr/bin/env python
"""Fleet trace stitcher (ADR-021): pull every member's flight-recorder
span rings and emit ONE offset-aligned Perfetto timeline with a process
lane per host — so a forwarded frame's journey (client → host A
io/coalesce → forward lane → host B dispatch/device → reply) reads as
one trace.

Two modes:

* **Server-side stitch** (default): ask one member to do the fan-out —
  ``GET /debug/trace?fleet=1`` merges every member's dump on the
  membership's live clock-offset estimates and rewrites forward-window
  spans to their client frame's trace id where the sender's
  (fragment → window) links allow it.

      python tools/fleet_trace.py http://member:8434 \\
          --token $DEBUG_TOKEN -o fleet_trace.json

* **Offline stitch** (``--offline``): pull each member's own
  ``/debug/trace`` + ``/healthz`` (for the peer clock offsets the
  reference member's membership measured) and merge locally with the
  SAME code (ratelimiter_tpu.fleet.tower.merge_traces) — for when a
  member cannot reach its peers' gateways but the operator box can.

The output loads directly in Perfetto (ui.perfetto.dev) or
chrome://tracing. Each host renders as its own process lane; follow a
``trace_id`` across lanes (forward-window spans carry the original id
plus a ``window_id`` arg after stitching).

The fleet map must declare each member's gateway port (``"http": N``
per host entry); members without one are reported as unreachable lanes.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.error


def _fail(msg: str) -> None:
    print(f"error: {msg}", file=sys.stderr)
    raise SystemExit(1)


def _summarize(payload: dict) -> None:
    events = [e for e in payload.get("traceEvents", ())
              if e.get("ph") == "X"]
    hosts = payload.get("otherData", {}).get("hosts", {})
    by_host: dict = {}
    traces_per_host: dict = {}
    for e in events:
        host = e.get("args", {}).get("host", "?")
        by_host[host] = by_host.get(host, 0) + 1
        tid = e.get("args", {}).get("trace_id")
        if tid and tid != "0" * 16:
            traces_per_host.setdefault(tid, set()).add(host)
    crossing = sorted(t for t, hs in traces_per_host.items()
                      if len(hs) > 1)
    print(f"hosts: {len(hosts)} "
          f"({sum(1 for h in hosts.values() if h.get('reachable'))} "
          f"reachable, "
          f"{sum(1 for h in hosts.values() if h.get('aligned'))} "
          f"clock-aligned)")
    for host, meta in sorted(hosts.items()):
        off = meta.get("mono_offset_ns")
        print(f"  {host}: pid={meta.get('pid')} "
              f"spans={by_host.get(host, 0)} "
              f"offset={'n/a' if off is None else f'{off / 1e6:+.3f}ms'}"
              f"{'' if meta.get('reachable') else '  [UNREACHABLE]'}")
    print(f"spans: {len(events)}  trace ids crossing hosts: "
          f"{len(crossing)}")
    for t in crossing[:8]:
        print(f"  {t} on {sorted(traces_per_host[t])}")


def stitched_via_member(base: str, token: str, timeout: float) -> dict:
    from ratelimiter_tpu.fleet.tower import fetch_json

    return fetch_json(base.rstrip("/") + "/debug/trace?fleet=1",
                      bearer=token, timeout=timeout)


def stitched_offline(base: str, token: str, timeout: float) -> dict:
    from ratelimiter_tpu.fleet.tower import fetch_json, merge_traces

    base = base.rstrip("/")
    health = fetch_json(base + "/healthz", timeout=timeout)
    fleet = health.get("fleet")
    if not fleet:
        _fail("--offline needs a fleet member (no fleet block on "
              "/healthz)")
    ref = fleet["self"]
    payloads = {ref: fetch_json(base + "/debug/trace", bearer=token,
                                timeout=timeout)}
    offsets: dict = {ref: 0}
    peers = fleet.get("peers") or {}
    for peer_id, entry in (fleet.get("hosts") or {}).items():
        if peer_id == ref:
            continue
        offsets[peer_id] = (peers.get(peer_id) or {}).get(
            "mono_offset_ns")
        http = entry.get("http")
        if not http:
            payloads[peer_id] = None
            continue
        host = entry.get("addr", "").rsplit(":", 1)[0]
        try:
            payloads[peer_id] = fetch_json(
                f"http://{host}:{http}/debug/trace", bearer=token,
                timeout=timeout)
        except Exception as exc:  # noqa: BLE001 — named gap
            print(f"warning: {peer_id} unreachable ({exc})",
                  file=sys.stderr)
            payloads[peer_id] = None
    return merge_traces(payloads, offsets, ref)


def main() -> None:
    ap = argparse.ArgumentParser(
        description="Stitch the fleet's flight-recorder rings into one "
                    "Perfetto timeline (ADR-021)")
    ap.add_argument("gateway", help="any member's HTTP gateway, e.g. "
                                    "http://host:8434")
    ap.add_argument("--token", default=None,
                    help="debug bearer token (--debug-token; assumed "
                         "fleet-uniform — it is passed through to "
                         "peers)")
    ap.add_argument("-o", "--out", default="fleet_trace.json",
                    help="output file (Perfetto/Chrome-trace JSON)")
    ap.add_argument("--offline", action="store_true",
                    help="merge locally from each member's own "
                         "/debug/trace instead of asking the member to "
                         "fan out")
    ap.add_argument("--timeout", type=float, default=10.0)
    args = ap.parse_args()
    try:
        payload = (stitched_offline(args.gateway, args.token,
                                    args.timeout) if args.offline
                   else stitched_via_member(args.gateway, args.token,
                                            args.timeout))
    except urllib.error.HTTPError as exc:
        _fail(f"{exc} — bad/missing --token, or the member runs "
              f"without --debug-trace/--flight-recorder")
    except Exception as exc:  # noqa: BLE001
        _fail(str(exc))
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(payload, f)
    _summarize(payload)
    print(f"wrote {args.out} — open in ui.perfetto.dev")


if __name__ == "__main__":
    main()
