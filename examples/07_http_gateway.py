"""HTTP interop: the server binary serving plain HTTP alongside the
binary protocol — 429 + X-RateLimit-* headers, exactly the reference's
flagship usage example (its docs/EXAMPLES.md weather API), curl-able."""

import json
import os
import signal
import socket
import subprocess
import sys
import urllib.error
import urllib.request


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


port, http_port = free_port(), free_port()
env = dict(os.environ)
repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
env["PYTHONPATH"] = os.pathsep.join(
    [repo] + env.get("PYTHONPATH", "").split(os.pathsep))
server = subprocess.Popen(
    [sys.executable, "-m", "ratelimiter_tpu.serving",
     "--backend", "exact", "--algorithm", "sliding_window",
     "--limit", "3", "--window", "60", "--port", str(port),
     # Reset over HTTP is OFF by default (quota-erase lever on a
     # curl-able surface); this demo token-gates it.
     "--http-port", str(http_port), "--http-reset-token", "demo-token"],
    env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
# Log lines (stderr) interleave before the ready banner — wait for the
# banner itself, or the first request races the gateway's bind.
for _ in range(50):
    line = server.stdout.readline().strip()
    print(line)
    if line.startswith("serving"):
        break

base = f"http://127.0.0.1:{http_port}"
for i in range(3):
    with urllib.request.urlopen(f"{base}/v1/allow?key=user:1") as r:
        body = json.loads(r.read())
        print(f"request {i}: 200 allowed remaining="
              f"{r.headers['X-RateLimit-Remaining']}")

try:
    urllib.request.urlopen(f"{base}/v1/allow?key=user:1")
except urllib.error.HTTPError as e:
    assert e.code == 429
    print(f"request 3: 429 Retry-After={e.headers['Retry-After']}s "
          f"X-RateLimit-Limit={e.headers['X-RateLimit-Limit']}")

# Key via the X-User-ID header (the reference example's convention).
req = urllib.request.Request(f"{base}/v1/allow",
                             headers={"X-User-ID": "user:2"})
with urllib.request.urlopen(req) as r:
    print(f"header key: 200 remaining={r.headers['X-RateLimit-Remaining']}")

# Reset over HTTP, then the key admits again. Without the bearer token
# the gateway answers 403 (reset is a guarded surface).
try:
    urllib.request.urlopen(urllib.request.Request(
        f"{base}/v1/reset?key=user:1", method="POST"))
    raise AssertionError("unauthenticated reset must 403")
except urllib.error.HTTPError as e:
    assert e.code == 403
    print("reset without token: 403")
urllib.request.urlopen(urllib.request.Request(
    f"{base}/v1/reset?key=user:1", method="POST",
    headers={"Authorization": "Bearer demo-token"}))
with urllib.request.urlopen(f"{base}/v1/allow?key=user:1") as r:
    print("after reset: 200")

with urllib.request.urlopen(f"{base}/healthz") as r:
    print("healthz:", json.loads(r.read()))

server.send_signal(signal.SIGTERM)
assert server.wait(timeout=15) == 0
print("OK")
