"""Multi-chip mesh limiting. Run with a virtual mesh on any host:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python examples/05_mesh.py
"""

import os

if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax

jax.config.update("jax_enable_x64", True)

if len(jax.devices()) < 2:
    print("SKIP: need >= 2 devices (see module docstring)")
    raise SystemExit(0)

from ratelimiter_tpu import Algorithm, Config, ManualClock, SketchParams
from ratelimiter_tpu.parallel import MeshSketchLimiter, make_mesh

mesh = make_mesh()
cfg = Config(algorithm=Algorithm.TPU_SKETCH, limit=10, window=60.0,
             sketch=SketchParams(depth=2, width=1024, sub_windows=6))

lim = MeshSketchLimiter(cfg, ManualClock(1.7e9), mesh=mesh, merge="gather")
out = lim.allow_batch(["hot"] * 64)
print(f"{len(mesh.devices.flat)}-device mesh, gather mode: "
      f"{out.allow_count}/64 admitted (bit-exact global limit=10)")
assert out.allow_count == 10
lim.close()
print("OK")
