"""gRPC surface + multi-pod shape: the server binary fronting the
checked-in proto contract (api/proto/ratelimiter.proto) via the grpcio
adapter, sharing one limiter with the binary protocol — and where to go
for the full two-pod deployment (deployments/).

Skips cleanly when the optional grpcio runtime or protoc is absent."""

import os
import signal
import subprocess
import sys

repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, repo)

try:
    from ratelimiter_tpu.serving.grpc_server import _load_pb2, grpc_available
except ImportError:
    grpc_available = lambda: False  # noqa: E731
if not grpc_available():
    print("SKIP: grpcio/protoc unavailable (the binary protocol and "
          "HTTP gateway serve the same contract)")
    sys.exit(0)

import grpc  # noqa: E402


def free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


port, grpc_port = free_port(), free_port()
env = dict(os.environ)
env["PYTHONPATH"] = os.pathsep.join([repo] +
                                    env.get("PYTHONPATH", "").split(os.pathsep))
server = subprocess.Popen(
    [sys.executable, "-m", "ratelimiter_tpu.serving",
     "--backend", "exact", "--algorithm", "token_bucket",
     "--limit", "5", "--window", "60", "--port", str(port),
     "--grpc-port", str(grpc_port)],
    env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
for _ in range(10):
    line = server.stdout.readline().strip()
    if line.startswith("serving"):
        print(line)
        break

pb2 = _load_pb2()
channel = grpc.insecure_channel(f"127.0.0.1:{grpc_port}")
call = lambda name, req_cls, resp_cls: channel.unary_unary(  # noqa: E731
    f"/ratelimiter.v1.RateLimiter/{name}",
    request_serializer=req_cls.SerializeToString,
    response_deserializer=resp_cls.FromString)
Allow = call("Allow", pb2.AllowRequest, pb2.AllowResponse)
AllowN = call("AllowN", pb2.AllowNRequest, pb2.AllowResponse)
Health = call("Health", pb2.HealthRequest, pb2.HealthResponse)

resp = AllowN(pb2.AllowNRequest(key="user:1", n=4))
print(f"AllowN(4): allowed={resp.allowed} remaining={resp.remaining}")
resp = Allow(pb2.AllowRequest(key="user:1"))
print(f"Allow:     allowed={resp.allowed} remaining={resp.remaining}")
resp = AllowN(pb2.AllowNRequest(key="user:1", n=2))
print(f"AllowN(2): allowed={resp.allowed} retry_after={resp.retry_after:.1f}s")
assert not resp.allowed

# Typed status mapping (proto footer): n=0 -> INVALID_ARGUMENT.
try:
    AllowN(pb2.AllowNRequest(key="user:1", n=0))
    raise AssertionError("n=0 must be INVALID_ARGUMENT")
except grpc.RpcError as e:
    print(f"n=0 -> {e.code().name}")
    assert e.code() == grpc.StatusCode.INVALID_ARGUMENT

h = Health(pb2.HealthRequest())
print(f"Health: serving={h.serving}")

channel.close()
server.send_signal(signal.SIGTERM)
assert server.wait(timeout=15) == 0
print("OK — for the two-pod (DCN + HTTP + shards) topology, run "
      "deployments/two_pod_local.sh")
