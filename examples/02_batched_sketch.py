"""The TPU hot path: batched decisions on the sketch backend.

Constant memory in key cardinality; one device dispatch per batch.
(Runs on whatever JAX backend is available — CPU works.)
"""

import jax

jax.config.update("jax_enable_x64", True)  # device backends need int64 state math

import numpy as np

from ratelimiter_tpu import Algorithm, Config, SketchParams, create_limiter

lim = create_limiter(
    Config(algorithm=Algorithm.TPU_SKETCH, limit=100, window=60.0,
           sketch=SketchParams(depth=4, width=1 << 14)),
    backend="sketch")

# String-key batch (hashed host-side by the native bulk hasher).
keys = [f"user:{i % 1000}" for i in range(4096)]
out = lim.allow_batch(keys)
print(f"batch of {len(out)}: {out.allow_count} allowed")

# Pre-hashed fast path: no string handling at all.
before = lim.memory_bytes()
h64 = np.arange(100_000, dtype=np.uint64)
out = lim.allow_hashed(h64)
print(f"100K distinct keys: {out.allow_count} allowed, "
      f"memory unchanged: {lim.memory_bytes() == before}")
lim.close()
print("OK")
