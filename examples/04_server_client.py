"""Serving end to end: spawn the server binary, drive it with both
clients, shut it down gracefully."""

import os
import signal
import socket
import subprocess
import sys

from ratelimiter_tpu.serving import Client

s = socket.socket()
s.bind(("127.0.0.1", 0))
port = s.getsockname()[1]
s.close()

env = dict(os.environ)
repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
env["PYTHONPATH"] = os.pathsep.join(
    [repo] + env.get("PYTHONPATH", "").split(os.pathsep))
server = subprocess.Popen(
    [sys.executable, "-m", "ratelimiter_tpu.serving",
     "--backend", "exact", "--algorithm", "token_bucket",
     "--limit", "3", "--window", "60", "--port", str(port)],
    env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
print(server.stdout.readline().strip())

with Client(port=port) as c:
    for i in range(4):
        res = c.allow("user:1")
        print(f"rpc {i}: allowed={res.allowed} remaining={res.remaining}")
    results = c.allow_batch(["a", "b", "a"])
    print(f"batch rpc: {[r.allowed for r in results]}")
    serving, uptime, decisions = c.health()
    print(f"health: serving={serving} decisions={decisions}")

server.send_signal(signal.SIGTERM)
assert server.wait(timeout=15) == 0
print("graceful shutdown: exit 0")
print("OK")
