"""Elastic resharding + zero-downtime operations (ADR-018).

Two halves of the elastic lifecycle:

1. **re-bucketing** — a sliced-mesh snapshot taken at one device count
   restores onto ANOTHER (in-process here): clean splits copy state
   verbatim, merges take the conservative union, so overrides survive
   exactly and the resharded mesh never over-admits relative to its
   source. The same math runs offline as ``tools/rebucket.py``.
2. **zero-downtime rolling restart** — a two-member fleet (real server
   subprocesses) under live FleetClient traffic: SIGTERM one member and
   its departure handoff moves ownership to the survivor BEFORE the
   socket closes (no client errors); restart it and the automatic
   rejoin give-back returns its ranges, counters intact.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4 \
        python examples/16_elastic.py

Runbook: docs/OPERATIONS.md §10 (scale-out, scale-in, rolling restart).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "1")  # device backends need x64
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=4").strip()


def part_one_rebucketing() -> None:
    import numpy as np

    from ratelimiter_tpu import Algorithm, Config, SketchParams
    from ratelimiter_tpu.checkpoint import save_state
    from ratelimiter_tpu.core.clock import ManualClock
    from ratelimiter_tpu.parallel.limiter import SlicedMeshLimiter

    print("=== 1. re-bucketing: restore a 4-slice snapshot onto 3 "
          "slices ===")
    cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=20,
                 window=600.0,
                 sketch=SketchParams(depth=2, width=2048, sub_windows=6))
    clock = ManualClock(1000.0)
    src = SlicedMeshLimiter(cfg, clock, n_devices=4)
    cfg = src.config
    rng = np.random.default_rng(0)
    keys = [f"user:{i}" for i in range(40)]
    for _ in range(6):
        src.allow_batch([keys[j] for j in rng.integers(0, 40, size=48)]
                        + keys[:4])
        clock.advance(30.0)
    src.set_override("user:3", 5)
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "mesh4.npz")
        kind, arrays, extra = src.capture_state()
        save_state(path, kind, cfg, arrays, extra)
        oracle = SlicedMeshLimiter(cfg, ManualClock(clock.now()),
                                   n_devices=4)
        oracle.restore(path)
        base = oracle.allow_batch(keys)
        for m in (3,):   # a prime count: every old slice contributes
            dst = SlicedMeshLimiter(cfg, ManualClock(clock.now()),
                                    n_devices=m)
            dst.restore(path)   # re-buckets instead of refusing
            out = dst.allow_batch(keys)
            over = int((out.allowed & ~base.allowed).sum())
            print(f"  4 -> {m} slices: override user:3 = "
                  f"{dst.get_override('user:3').limit}, "
                  f"allowed {int(out.allowed.sum())}/{len(keys)} "
                  f"(source {int(base.allowed.sum())}), "
                  f"over-admissions vs source = {over}")
            dst.close()
        oracle.close()
    src.close()


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def spawn(port, cfgpath, self_id, snap):
    env = dict(os.environ)
    # Private jit compiles: the shared persistent cache can hold torn
    # entries (kill -9 tests) and aborts XLA-CPU when the handoff
    # compiles new shapes mid-serving.
    env["RATELIMITER_TPU_COMPILE_CACHE"] = ""
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + env.get("PYTHONPATH", "").split(os.pathsep))
    return subprocess.Popen(
        [sys.executable, "-m", "ratelimiter_tpu.serving",
         "--backend", "sketch", "--limit", "100", "--window", "600",
         "--sketch-width", "8192", "--sub-windows", "6",
         "--port", str(port), "--no-prewarm",
         "--snapshot-dir", snap, "--snapshot-interval", "500",
         "--fleet-config", cfgpath, "--fleet-self", self_id,
         "--fleet-forward-deadline", "60",
         "--fleet-heartbeat", "0.3", "--fleet-dead-after", "1.5"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)


def wait_banner(proc):
    while True:
        line = proc.stdout.readline()
        if not line:
            raise SystemExit("member died at start")
        if line.startswith("serving"):
            return


def fetch_map(port):
    from ratelimiter_tpu.fleet.config import FleetMap
    from ratelimiter_tpu.serving.client import Client

    with Client(port=port, timeout=60) as c:
        return FleetMap.from_dict(c.fleet_map())


def part_two_rolling_restart() -> None:
    from ratelimiter_tpu.serving.client import FleetClient

    print("=== 2. rolling restart: SIGTERM -> departure handoff -> "
          "restart -> rejoin ===")
    with tempfile.TemporaryDirectory() as tmp:
        pa, pb = free_port(), free_port()
        snaps = [os.path.join(tmp, "sa"), os.path.join(tmp, "sb")]
        fleet = {"buckets": 32, "epoch": 1, "hosts": [
            {"id": "a", "host": "127.0.0.1", "port": pa,
             "ranges": [[0, 16]], "successor": "b",
             "snapshot_dir": snaps[0]},
            {"id": "b", "host": "127.0.0.1", "port": pb,
             "ranges": [[16, 32]], "successor": "a",
             "snapshot_dir": snaps[1]}]}
        cfgpath = os.path.join(tmp, "fleet.json")
        with open(cfgpath, "w", encoding="utf-8") as f:
            json.dump(fleet, f)
        a = spawn(pa, cfgpath, "a", snaps[0])
        b = spawn(pb, cfgpath, "b", snaps[1])
        try:
            wait_banner(a)
            wait_banner(b)
            fc = FleetClient(fleet, call_timeout=60)
            served = errors = 0
            for i in range(20):
                try:
                    fc.allow_batch([f"k:{j}" for j in range(32)])
                    served += 32
                except Exception:  # noqa: BLE001
                    errors += 1
            print(f"  steady: served {served} decisions, {errors} "
                  f"errors")
            t0 = time.time()
            a.send_signal(signal.SIGTERM)
            rc = a.wait(timeout=120)
            m_now = fetch_map(pb)
            print(f"  SIGTERM a: exit code {rc}, map epoch "
                  f"{m_now.epoch}, b owns "
                  f"{m_now.owned_buckets('b')}/32 buckets "
                  f"({time.time() - t0:.1f}s)")
            for i in range(10):
                fc.allow_batch([f"k:{j}" for j in range(32)])
            print("  traffic kept flowing through b (forward/redirect "
                  "window)")
            a = spawn(pa, cfgpath, "a", snaps[0])
            wait_banner(a)
            t0 = time.time()
            while time.time() - t0 < 60:
                m_now = fetch_map(pb)
                if m_now.host("a").ranges:
                    break
                time.sleep(0.2)
            print(f"  restarted a: rejoin handed back "
                  f"{m_now.host('a').ranges} at epoch {m_now.epoch} "
                  f"({time.time() - t0:.1f}s)")
            fc.close()
        finally:
            for pr in (a, b):
                if pr.poll() is None:
                    pr.terminate()
            for pr in (a, b):
                try:
                    pr.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    pr.kill()


if __name__ == "__main__":
    part_one_rebucketing()
    part_two_rolling_restart()
    print("elastic lifecycle OK")
