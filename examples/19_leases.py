"""Client-embedded quota leases: hot-key decisions at memory speed
(ADR-022).

Every decision the serving tier makes normally costs a wire RTT. The
lease tier moves the hottest keys off the wire entirely: the server
debits a bounded token budget from the limiter UPFRONT and hands it to
the client, whose ``allow``/``allow_n`` then answer leased keys from an
in-process counter — nanoseconds, no socket. Safety is structural:
because the whole budget was charged through the real decide path
before the first local answer, no client behaviour (crash, partition,
lost revocation) can push global admissions past the limit; the worst
case is unused budget reading as consumed. This example shows the full
loop on one asyncio-door server:

1. a hot key crosses the client's hotness threshold and gets leased;
2. local answers vs wire answers, timed side by side;
3. a policy override tightens the key → the server pushes a
   revocation and the cache drops the lease mid-flight;
4. the server-side lease metric families on the registry.

Run on any host:

    JAX_PLATFORMS=cpu python examples/19_leases.py

The served form (the flags live on the real binary too):

    python -m ratelimiter_tpu.serving --backend sketch --leases \
        --lease-ttl 2 --lease-budget 256
"""

import os

if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import asyncio
import time

from ratelimiter_tpu import Algorithm, Config, ManualClock, create_limiter
from ratelimiter_tpu.leases import LeaseManager
from ratelimiter_tpu.observability import Registry
from ratelimiter_tpu.serving import AsyncClient, RateLimitServer

T0 = 1_700_000_000.0


async def main() -> None:
    # Exact backend, frozen window: admissions are bit-exact, so the
    # debit-upfront arithmetic below is visible in the numbers.
    cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=500_000,
                 window=60.0, key_prefix="")
    lim = create_limiter(cfg, backend="exact", clock=ManualClock(T0))
    reg = Registry()
    mgr = LeaseManager(lim, ttl=2.0, default_budget=50_000, registry=reg)
    server = RateLimitServer(lim, "127.0.0.1", 0, leases=mgr)
    await server.start()

    client = await AsyncClient.connect(server.host, server.port)
    cache = await client.enable_leases(interval=0.02, hot_after=4,
                                       hot_window=5.0, low_water=0.5)

    # --- 1. heat the key: a few wire decisions trip the hotness
    # detector, the background maintenance grants a lease.
    for _ in range(6):
        await client.allow("user:hot")
    for _ in range(200):
        if cache.status()["leased_keys"]:
            break
        await asyncio.sleep(0.02)
    assert cache.status()["leased_keys"] == 1, cache.status()
    print("== lease granted ==")
    print(f"  server: {mgr.status()['active']} active, "
          f"{int(mgr.status()['granted_total'])} granted")

    # --- 2. memory-speed vs wire, same client, same key.
    n = 2000
    t0 = time.perf_counter()
    for _ in range(n):
        await client.allow("user:hot")          # local: lease cache
    t_local = time.perf_counter() - t0
    # Rotate over 1000 cold keys: 2 visits each stays under hot_after,
    # so this loop never trips a lease — every decision is a real RTT.
    for i in range(1000):
        await client.allow(f"cold:{i}")         # warm the key table
    t0 = time.perf_counter()
    for i in range(n):
        await client.allow(f"cold:{i % 1000}")  # wire: full RTT
    t_wire = time.perf_counter() - t0
    st = cache.status()
    print("== decision cost, same client ==")
    print(f"  leased  : {n / t_local:,.0f}/s "
          f"({t_local / n * 1e6:.2f} us/decision)")
    print(f"  wire    : {n / t_wire:,.0f}/s "
          f"({t_wire / n * 1e6:.2f} us/decision)")
    print(f"  local answers so far: {st['local_answers']}")
    assert st["local_answers"] >= n

    # --- 3. a policy change must not leave stale budgets answering:
    # the override handler revokes the key's leases with a push frame.
    await client.set_override("user:hot", limit=10)
    for _ in range(200):
        if not cache.status()["leased_keys"]:
            break
        await asyncio.sleep(0.02)
    assert cache.status()["leased_keys"] == 0, cache.status()
    r = await client.allow("user:hot")          # back on the wire
    print("== revocation push (policy override limit=10) ==")
    print(f"  cache leases after push: {cache.status()['leased_keys']}")
    print(f"  wire decision under new limit: allowed={r.allowed}")

    # --- 4. the observable trail.
    print("== server lease families (/metrics) ==")
    for line in reg.render().splitlines():
        if line.startswith("rate_limiter_lease") and " " in line \
                and not line.startswith("# HELP"):
            print(" ", line)

    await client.close()
    await server.shutdown()
    lim.close()
    print("OK")


if __name__ == "__main__":
    asyncio.run(main())
