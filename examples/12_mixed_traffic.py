"""Mixed traffic on a sliced mesh: the scatter-gather scheduler (ADR-013).

MIXED frames — frames whose keys span several device slices, what any
un-sharded load balancer sends — used to fork-join across every device
queue (16x collapse in MULTICHIP_r06). The scheduler splits each frame
once, coalesces every frame that arrives within one batching window
into ONE dispatch per touched device, and answers each frame from its
row range of the window result. Run with a virtual mesh on any host:

    XLA_FLAGS=--xla_force_host_platform_device_count=4 JAX_PLATFORMS=cpu \
        python examples/12_mixed_traffic.py

The served form (the C++ loadgen's slice-spread knob drives the same
shape: spread=1 affine .. spread=n uniform mixed):

    python -m ratelimiter_tpu.serving --backend mesh --mesh-devices 8 \
        --native --inflight 1 --max-batch 16384 --max-delay-us 1000
"""

import os

if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax

jax.config.update("jax_enable_x64", True)

if len(jax.devices()) < 4:
    print("SKIP: need >= 4 devices (see module docstring)")
    raise SystemExit(0)

import asyncio

import numpy as np

from ratelimiter_tpu import Algorithm, Config, ManualClock, SketchParams
from ratelimiter_tpu.algorithms.sketch import SketchLimiter
from ratelimiter_tpu.observability import Registry
from ratelimiter_tpu.parallel import SlicedMeshLimiter
from ratelimiter_tpu.serving import MicroBatcher

T0 = 1.7e9
cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=5, window=60.0,
             sketch=SketchParams(depth=2, width=1024, sub_windows=6))
mesh = SlicedMeshLimiter(cfg, ManualClock(T0), n_devices=4)

# Six "clients" each submit a MIXED frame (ids spanning all 4 slices)
# in the same batching window. The micro-batcher concatenates them in
# arrival order, launches ONCE (= one padded sub-dispatch per touched
# device), and resolves each client's future from its own row range.
rng = np.random.default_rng(0)
hot = np.uint64(0xBEEF)
frames = []
for _ in range(6):
    ids = rng.integers(1, 1 << 40, size=64, dtype=np.uint64)
    ids[::16] = hot                     # a hot id recurring across frames
    frames.append(ids)

reg = Registry()


async def clients():
    b = MicroBatcher(mesh, max_batch=1 << 14, max_delay=2e-3,
                     inflight=4, registry=reg)
    futs = [b.submit_hashed_nowait(f, np.ones(64, dtype=np.int64))
            for f in frames]
    outs = await asyncio.gather(*futs)
    await b.drain()
    b.close()
    return outs


outs = asyncio.run(clients())
dispatches = reg.get("rate_limiter_server_batch_size").count()
print(f"{len(frames)} mixed frames of 64 ids -> {dispatches} window "
      f"dispatch(es); each client got its own {len(outs[0])}-row result")

# Same-key ordering is ARRIVAL order across the coalesced frames: the
# hot id appears 4x per frame, 24x in the window, limit=5 — exactly the
# FIRST five occurrences are admitted, counted across frame boundaries.
hot_decisions = np.concatenate([o.allowed[f == hot]
                                for o, f in zip(outs, frames)])
assert hot_decisions.sum() == 5 and bool(np.all(hot_decisions[:5]))
print(f"hot id across the window: {hot_decisions[:8].tolist()}... "
      "(first 5 admitted, arrival-ordered)")

# The decisions are bit-identical to single-device oracles fed each
# slice's ids in arrival order — coalescing changes the batching, not
# the decision stream.
window = np.concatenate(frames)
allowed = np.concatenate([o.allowed for o in outs])
owners = mesh.owner_of_id(window)
for dev in range(4):
    idx = np.flatnonzero(owners == dev)
    oracle = SketchLimiter(cfg, ManualClock(T0))
    np.testing.assert_array_equal(allowed[idx],
                                  oracle.allow_ids(window[idx]).allowed)
    oracle.close()
print("bit-identical to per-slice single-device oracles")

# Embedders batching their own frames use the same seam directly:
# launch the window, slice the result — views, no copies. (A fresh
# mesh, because the batcher above already consumed the hot id's quota.)
mesh2 = SlicedMeshLimiter(cfg, ManualClock(T0), n_devices=4)
res = mesh2.resolve(mesh2.launch_ids(window, wire=True))
first = res.rows(0, 64)                  # client 0's rows
assert first.remaining.base is not None  # a view over the window result
np.testing.assert_array_equal(first.allowed, outs[0].allowed)
print("BatchResult.rows(): zero-copy per-frame views of one window")

mesh2.close()
mesh.close()
print("OK")
