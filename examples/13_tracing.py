"""Flight-recorder tracing: follow one frame through every stage (ADR-014).

The flight recorder stamps per-stage spans (io -> coalesce -> launch ->
device -> barrier/slice -> resolve -> encode) into per-thread ring
buffers at clock-read cost, and a caller-minted trace id rides the wire
so ONE id connects the client span to every server-side stage it
crossed. This example traces a mixed mesh frame end-to-end and writes a
Perfetto-loadable dump. Run with a virtual mesh on any host:

    XLA_FLAGS=--xla_force_host_platform_device_count=2 JAX_PLATFORMS=cpu \
        python examples/13_tracing.py

The served form (dump via the bearer-gated HTTP endpoint, §6 of
docs/OPERATIONS.md):

    python -m ratelimiter_tpu.serving --backend mesh --flight-recorder \
        --http-port 8433 --debug-trace --debug-token s3cret
    curl -H 'Authorization: Bearer s3cret' \
        http://localhost:8433/debug/trace > trace.json   # -> ui.perfetto.dev
"""

import os

if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax

jax.config.update("jax_enable_x64", True)

if len(jax.devices()) < 2:
    print("SKIP: need >= 2 devices (see module docstring)")
    raise SystemExit(0)

import asyncio
import json

import numpy as np

from ratelimiter_tpu import Algorithm, Config, SketchParams
from ratelimiter_tpu.observability import Registry, tracing
from ratelimiter_tpu.parallel import SlicedMeshLimiter
from ratelimiter_tpu.serving import AsyncClient, RateLimitServer

# Tracing is OFF by default (zero overhead: hot paths check one module
# global and skip everything). enable() turns it on process-wide;
# attaching a registry also derives rate_limiter_stage_seconds{stage=..}
# histograms — with trace-id exemplars — at scrape time.
reg = Registry()
rec = tracing.enable(capacity=4096, registry=reg)

cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=100, window=60.0,
             sketch=SketchParams(depth=2, width=1024, sub_windows=6))
mesh = SlicedMeshLimiter(cfg, n_devices=2)


async def traced_request():
    srv = RateLimitServer(mesh, max_batch=4096, max_delay=200e-6)
    await srv.start()
    c = await AsyncClient.connect(srv.host, srv.port)

    # The caller mints the id and samples the request by passing it:
    # trace_id= flags a tiny extension onto the wire frame, and every
    # stage the frame crosses stamps a span under that id. Wrap the
    # call in a client span so the dump shows wire+server time too.
    tid = tracing.new_trace_id()
    ids = np.arange(1, 257, dtype=np.uint64)      # spans BOTH slices
    t0 = tracing.now()
    out = await c.allow_hashed(ids, trace_id=tid)
    tracing.record("client", t0, tracing.now(), trace_id=tid,
                   batch=len(out))
    assert out.allowed.all()

    await c.close()
    await srv.shutdown()
    return tid


tid = asyncio.run(traced_request())

# The span tree for that one frame: client > io > coalesce/queue/launch
# > device > barrier (one per frame, ADR-013) + one slice span per
# touched device > resolve > encode.
mine = sorted((s for s in rec.dump() if s["trace_id"] == tid),
              key=lambda s: s["t_start_ns"])
t0 = mine[0]["t_start_ns"]
print(f"trace {tid:016x}: {len(mine)} spans")
for s in mine:
    off = (s["t_start_ns"] - t0) / 1e3
    dur = (s["t_end_ns"] - s["t_start_ns"]) / 1e3
    shard = f" slice={s['shard']}" if s["shard"] >= 0 else ""
    print(f"  +{off:8.1f}us  {s['stage']:<8} {dur:8.1f}us"
          f"  batch={s['batch']}{shard}")
assert {"client", "io", "launch", "device", "barrier", "slice",
        "resolve", "encode"} <= {s["stage"] for s in mine}

# chrome_trace() renders the Chrome trace-event JSON that Perfetto
# (ui.perfetto.dev) and chrome://tracing open directly — the same
# payload GET /debug/trace serves.
path = "/tmp/ratelimiter_trace.json"
with open(path, "w") as f:
    json.dump(rec.chrome_trace(), f)
print(f"Perfetto-loadable dump: {path}")

# Aggregates ride the normal metrics scrape: stage_summary() for quick
# looks, rate_limiter_stage_seconds{stage=...} on /metrics for fleets
# (OpenMetrics rendering ties buckets to example trace ids).
summary = rec.stage_summary()
device = summary["device"]
print(f"stage_summary: device mean {device['mean_us']}us "
      f"over {device['count']} span(s)")
text = reg.render(openmetrics=True)
assert "rate_limiter_stage_seconds" in text

mesh.close()
tracing.disable()
print("OK")
