"""Decorators + checkpoint/restore in one flow."""

import jax

jax.config.update("jax_enable_x64", True)  # device backends need int64 state math

import tempfile

from ratelimiter_tpu import Algorithm, Config, ManualClock, create_limiter
from ratelimiter_tpu.observability import MetricsDecorator, Registry

clock = ManualClock(1_700_000_000.0)
cfg = Config(algorithm=Algorithm.TOKEN_BUCKET, limit=10, window=10.0)
reg = Registry()
lim = MetricsDecorator(
    create_limiter(cfg, backend="sketch", clock=clock), reg)

assert lim.allow_n("k", 10).allowed
assert not lim.allow("k").allowed

with tempfile.NamedTemporaryFile(suffix=".npz") as f:
    lim.save(f.name)                       # decorator passes through
    lim2 = create_limiter(cfg, backend="sketch", clock=clock)
    lim2.restore(f.name)
    assert not lim2.allow("k").allowed     # restored state denies too
    clock.advance(1.0)
    assert lim2.allow("k").allowed         # 1 token refilled post-restore
    lim2.close()

print(reg.render().splitlines()[2])        # one emitted metric line
lim.close()
print("OK")
