"""The fleet control tower: cross-host traces, merged rollup, event
journal (ADR-021).

Spins up a TWO-member fleet (real server subprocesses, all
observability on), drives traced traffic across the forwarding hop,
then reads the three tower surfaces any ONE member answers for the
whole fleet:

1. ``GET /debug/trace?fleet=1`` — ONE offset-aligned Perfetto timeline
   (a process lane per host); the traced frame's spans cross the hop
   under one trace id (the forward window's wire id is linked back to
   the client frame host-side);
2. ``GET /v1/fleet/status`` — the merged rollup: audit tallies summed
   with Wilson bounds recomputed over the merged n, fleet-wide top-K
   consumers joined by (h1,h2) token, pooled SLO burn, per-member
   liveness/epochs;
3. ``GET /debug/events?fleet=1`` — the control-plane journal, merged:
   a policy mutation on member h1 read from member h0, host-tagged and
   clock-aligned.

    JAX_PLATFORMS=cpu python examples/18_control_tower.py

CLI twins: tools/fleet_trace.py and tools/fleet_status.py.
Runbook: docs/OPERATIONS.md §12 (incident triage).
"""

import json
import os
import socket
import subprocess
import sys
import tempfile
import time
import urllib.request

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

TOKEN = "example-debug-token"


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def spawn(port, http_port, cfgpath, self_id):
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + env.get("PYTHONPATH", "").split(os.pathsep))
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.Popen(
        [sys.executable, "-m", "ratelimiter_tpu.serving",
         "--backend", "sketch", "--limit", "100", "--window", "600",
         "--sketch-width", "8192", "--sub-windows", "6",
         "--port", str(port), "--http-port", str(http_port),
         "--no-prewarm",
         "--fleet-config", cfgpath, "--fleet-self", self_id,
         "--fleet-heartbeat", "0.3", "--fleet-dead-after", "30",
         # --no-prewarm: the first forwarded window pays the receiver's
         # XLA compile; the forward deadline must cover it.
         "--fleet-forward-deadline", "60",
         # The control tower's inputs: recorder + audit + hh + journal.
         "--flight-recorder", "--debug-token", TOKEN,
         "--audit", "--audit-sample", "1", "--hh-slots", "16",
         "--http-policy-token", "policy-token"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)


def wait_banner(proc):
    while True:
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError("member died during start")
        if line.startswith("serving"):
            return


def get(url, token=None):
    req = urllib.request.Request(url)
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    with urllib.request.urlopen(req, timeout=15) as r:
        return json.loads(r.read())


def post(url, token=None):
    req = urllib.request.Request(url, method="POST")
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    with urllib.request.urlopen(req, timeout=15) as r:
        return json.loads(r.read())


def main() -> None:
    from ratelimiter_tpu.observability import tracing
    from ratelimiter_tpu.serving.client import Client

    ports = [free_port(), free_port()]
    https = [free_port(), free_port()]
    fleet = {"buckets": 32, "epoch": 1, "hosts": [
        {"id": "h0", "host": "127.0.0.1", "port": ports[0],
         "http": https[0], "ranges": [[0, 16]]},
        {"id": "h1", "host": "127.0.0.1", "port": ports[1],
         "http": https[1], "ranges": [[16, 32]]},
    ]}
    with tempfile.TemporaryDirectory() as tmp:
        cfgpath = os.path.join(tmp, "fleet.json")
        with open(cfgpath, "w", encoding="utf-8") as f:
            json.dump(fleet, f)
        print("== starting a 2-member fleet (all observability on) ==")
        members = [spawn(ports[i], https[i], cfgpath, f"h{i}")
                   for i in range(2)]
        try:
            for m in members:
                wait_banner(m)
            # Traced traffic through member h0: half the ids are owned
            # by h1 and cross the forwarding hop.
            c = Client(port=ports[0])
            trace_id = tracing.new_trace_id()
            c.allow_hashed(np.arange(1, 201, dtype=np.uint64),
                           trace_id=trace_id)
            hot = np.repeat(np.arange(1, 9, dtype=np.uint64), 10)
            for _ in range(6):
                c.allow_hashed(hot)   # hh promotions on both members
            c.close()
            time.sleep(1.5)          # heartbeats estimate clock offsets

            print("\n== 1. stitched fleet trace "
                  "(GET /debug/trace?fleet=1) ==")
            tr = get(f"http://127.0.0.1:{https[0]}/debug/trace?fleet=1",
                     TOKEN)
            t_hex = f"{trace_id:016x}"
            spans = [e for e in tr["traceEvents"] if e.get("ph") == "X"]
            mine = [e for e in spans
                    if e["args"].get("trace_id") == t_hex]
            print(f"   spans total: {len(spans)}; under our trace id: "
                  f"{len(mine)} across hosts "
                  f"{sorted({e['args']['host'] for e in mine})}")
            for e in sorted(mine, key=lambda e: e["ts"])[:12]:
                print(f"     {e['args']['host']:>3} {e['name']:<10} "
                      f"{e['dur']:>9.1f}us"
                      + ("  (wire window "
                         f"{e['args']['window_id'][:8]}…)"
                         if "window_id" in e["args"] else ""))
            out = os.path.join(tmp, "fleet_trace.json")
            with open(out, "w", encoding="utf-8") as f:
                json.dump(tr, f)
            print(f"   full timeline written to {out} "
                  f"(open in ui.perfetto.dev)")

            print("\n== 2. merged rollup (GET /v1/fleet/status) ==")
            st = get(f"http://127.0.0.1:{https[1]}/v1/fleet/status")
            print(f"   members reachable: {st['reachable']}/"
                  f"{st['members']}, epoch {st['epoch']} "
                  f"(converged={st['epoch_converged']})")
            a = st.get("audit") or {}
            print(f"   merged audit: {a.get('samples')} samples, "
                  f"false-deny {a.get('false_deny_rate')} "
                  f"wilson95 {a.get('false_deny_wilson95')}")
            for i, row in enumerate(
                    (st.get("consumers") or {}).get("top", [])[:3], 1):
                print(f"   top consumer #{i}: {row['consumer']} "
                      f"mass={row['in_window']} hosts="
                      f"{sorted(row['hosts'])}")

            print("\n== 3. fleet event journal "
                  "(GET /debug/events?fleet=1) ==")
            post(f"http://127.0.0.1:{https[1]}/v1/policy"
                 f"?key=vip&limit=500", "policy-token")
            evs = get(f"http://127.0.0.1:{https[0]}/debug/events"
                      f"?fleet=1", TOKEN)
            for e in evs["events"][-6:]:
                print(f"   [{e['host']}] {e['category']}/{e['action']} "
                      f"actor={e['actor'] or '-'} "
                      f"payload={json.dumps(e['payload'])[:60]}")
            print("\n   (the h1 policy mutation is visible from h0 — "
                  "one journal, fleet-wide)")
        finally:
            for m in members:
                m.terminate()
            for m in members:
                try:
                    m.wait(timeout=15)
                except subprocess.TimeoutExpired:
                    m.kill()
    print("\nOK — one fleet, one timeline, one rollup, one journal.")


if __name__ == "__main__":
    main()
