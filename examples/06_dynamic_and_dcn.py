"""Dynamic limit updates + cross-region (DCN) slab exchange."""

import jax

jax.config.update("jax_enable_x64", True)  # device backends need int64 state math

from ratelimiter_tpu import Algorithm, Config, ManualClock, SketchParams, create_limiter
from ratelimiter_tpu.parallel import DcnMirrorGroup

cfg = Config(algorithm=Algorithm.TPU_SKETCH, limit=10, window=6.0,
             sketch=SketchParams(depth=4, width=4096, sub_windows=6))

# -- dynamic limits: consumption stands, new limit governs ------------
clock = ManualClock(1_700_000_000.0)
lim = create_limiter(cfg, backend="sketch", clock=clock)
assert lim.allow_n("k", 10).allowed
assert not lim.allow("k").allowed
lim.update_limit(15)
res = lim.allow_n("k", 5)
print(f"after raise to 15: 5 more allowed={res.allowed} "
      f"(consumed 10 stands)")
lim.close()

# -- DCN: two 'regions' exchanging completed sub-window slabs ---------
clocks = [ManualClock(1_700_000_000.0) for _ in range(2)]
pods = [create_limiter(cfg, backend="sketch", clock=c) for c in clocks]
group = DcnMirrorGroup(pods)

print(f"region A admits: {pods[0].allow_batch(['hot'] * 12).allow_count}")
print(f"region B admits: {pods[1].allow_batch(['hot'] * 12).allow_count} "
      "(hasn't heard from A yet — bounded staleness)")
for c in clocks:
    c.advance(1.0)             # complete the sub-window
for p in pods:
    p.allow("tick")
group.sync()                   # any transport works; here in-process
print(f"after sync, region B: allowed={pods[1].allow('hot').allowed} "
      "(global history visible)")
for p in pods:
    p.close()
print("OK")
