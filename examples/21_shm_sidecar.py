"""Zero-syscall shared-memory wire lane: co-located frames at device
speed (ADR-025).

A rate-limit sidecar usually shares the host with its callers, yet every
decision still pays the full socket toll: two syscalls plus a kernel
copy per frame, each way. The shm lane removes all of it for same-host
traffic. A client connects normally (tcp or uds), then sends one
T_SHM_HELLO; the server maps a pair of single-producer/single-consumer
rings in /dev/shm and from then on frames — the EXISTING wire framing,
byte for byte — move as memory writes with a bounded-spin-then-eventfd
doorbell. The socket stays open but silent: it is the liveness channel
(peer death = socket close) and the auth boundary (the hello runs under
whatever the connection already negotiated).

This example shows the ladder end-to-end on one asyncio-door server:

1. plain tcp client and shm-upgraded client answering the same keys;
2. per-call latency, tcp vs shm, same loop, same limiter;
3. the transport observability block: per-transport connection counts,
   ring occupancy/high-water, doorbell-vs-spin counters;
4. the off-by-default pin: a server without ``shm=True`` answers the
   hello with a typed error and nothing else changes.

Run on any host:

    JAX_PLATFORMS=cpu python examples/21_shm_sidecar.py

The served form (same flags on the real binary, both doors):

    python -m ratelimiter_tpu.serving --backend sketch --native --shm
    python -m ratelimiter_tpu.serving --listen unix:/run/rl.sock --shm
"""

import os

if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import asyncio
import time

from ratelimiter_tpu import (
    Algorithm,
    Config,
    InvalidConfigError,
    ManualClock,
    create_limiter,
)
from ratelimiter_tpu.serving import AsyncClient, RateLimitServer

T0 = 1_700_000_000.0


async def timed_calls(client, key: str, n: int) -> float:
    t0 = time.perf_counter()
    for _ in range(n):
        res = await client.allow(key)
        assert res.allowed
    return (time.perf_counter() - t0) / n * 1e6  # µs/call


async def main() -> None:
    cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=10_000_000,
                 window=60.0, key_prefix="")
    lim = create_limiter(cfg, backend="exact", clock=ManualClock(T0))
    server = RateLimitServer(lim, "127.0.0.1", 0, shm=True)
    await server.start()

    # -- 1. the same lanes over both rungs -----------------------------
    tcp = await AsyncClient.connect(server.host, server.port)
    shm = await AsyncClient.connect(server.host, server.port,
                                    transport="shm")
    for c in (tcp, shm):
        assert (await c.allow("api:GET /v1/users")).allowed
        batch = await c.allow_batch(["t:1", "t:2", "t:1"])
        assert [r.allowed for r in batch] == [True, True, True]

    # -- 2. per-call latency, same loop, same limiter ------------------
    n = 2000
    us_tcp = await timed_calls(tcp, "bench:tcp", n)
    us_shm = await timed_calls(shm, "bench:shm", n)
    print(f"per-call latency over {n} calls: "
          f"tcp {us_tcp:.1f} us  shm {us_shm:.1f} us")

    # -- 3. transport observability ------------------------------------
    st = server.transport_stats()
    print("connections by transport:", st["connections"])
    sh = st["shm"]
    print(f"shm lanes active={sh['lanes_active']} "
          f"records in/out={sh['records_in']}/{sh['records_out']} "
          f"spin-hits={sh['spin_hits']} "
          f"doorbell-wakes={sh['doorbell_wakes']} "
          f"req-ring high-water={sh['req_ring_highwater_bytes']}B")
    assert st["connections"]["shm"] == 1
    assert sh["records_in"] >= n

    await tcp.close()
    await shm.close()
    await server.shutdown()

    # -- 4. off by default: the hello is a typed refusal ---------------
    plain = RateLimitServer(lim, "127.0.0.1", 0)  # no shm=True
    await plain.start()
    try:
        await AsyncClient.connect(plain.host, plain.port, transport="shm")
        raise AssertionError("hello should have been refused")
    except InvalidConfigError as exc:
        print(f"shm off => typed refusal: {exc}")
    await plain.shutdown()
    lim.close()
    print("OK")


if __name__ == "__main__":
    asyncio.run(main())
