"""Slice-parallel serving: the mesh backend (ADR-012).

One device-pinned sketch slice per device, keys hash-routed to their
owning slice, decide path collective-free — serving throughput scales
with the slice. Run with a virtual mesh on any host:

    XLA_FLAGS=--xla_force_host_platform_device_count=4 JAX_PLATFORMS=cpu \
        python examples/11_mesh_serving.py

The same thing as a server (both front doors):

    python -m ratelimiter_tpu.serving --backend mesh --mesh-devices 4
    python -m ratelimiter_tpu.serving --backend mesh --mesh-devices 4 \
        --native --inflight 1     # CPU mesh: see docs/OPERATIONS.md §2
"""

import os

if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax

jax.config.update("jax_enable_x64", True)

if len(jax.devices()) < 4:
    print("SKIP: need >= 4 devices (see module docstring)")
    raise SystemExit(0)

import numpy as np

from ratelimiter_tpu import (
    Algorithm,
    CheckpointError,
    Config,
    ManualClock,
    SketchParams,
    create_limiter,
)
from ratelimiter_tpu.algorithms.sketch import SketchLimiter

T0 = 1.7e9
cfg = Config(algorithm=Algorithm.SLIDING_WINDOW, limit=10, window=60.0,
             sketch=SketchParams(depth=2, width=1024, sub_windows=6))

# --backend mesh in library form: one slice per device, hash routing.
mesh = create_limiter(cfg, backend="mesh", clock=ManualClock(T0),
                      n_devices=4)
print(f"{mesh.n_slices} slices on:",
      [str(s._device) for s in mesh.slices])

# A hot key is globally exact: its traffic all lands on ONE device.
out = mesh.allow_batch(["hot"] * 64)
assert out.allow_count == 10
print(f"hot key: {out.allow_count}/64 admitted "
      f"(owner = device {mesh.owner_of_key('hot')}, collective-free)")

# The oracle property: for the keys a device owns, decisions are
# bit-identical to a single-device limiter fed exactly that traffic.
keys = [f"user:{i}" for i in range(200)]
got = mesh.allow_batch(keys)
owners = mesh.owner_of_hash(mesh._hash(keys))
oracle = SketchLimiter(cfg, ManualClock(T0))
idx = np.flatnonzero(owners == 0)
ref = oracle.allow_batch([keys[i] for i in idx])
np.testing.assert_array_equal(got.allowed[idx], ref.allowed)
print(f"device 0 owns {idx.size}/200 keys — bit-identical to the "
      "single-device oracle")
oracle.close()

# The raw-id lane routes by splitmix64(id) — same router as the native
# door's T_ALLOW_HASHED parse; pipelined launch/resolve fans each frame
# out to its owning devices concurrently.
ids = np.arange(1, 501, dtype=np.uint64)
t = mesh.launch_ids(ids)
res = mesh.resolve(t)
print(f"raw-id frame: {res.allow_count}/500 admitted across "
      f"{len(set(mesh.owner_of_id(ids).tolist()))} devices")

# Snapshots carry the slice count and refuse a different mesh size.
import tempfile

path = os.path.join(tempfile.mkdtemp(), "mesh.npz")
mesh.save(path)
smaller = create_limiter(cfg, backend="mesh", clock=ManualClock(T0),
                         n_devices=2)
try:
    smaller.restore(path)
except CheckpointError as exc:
    print(f"device-count change refused: {str(exc)[:80]}...")
smaller.close()
mesh.close()
print("OK")
