"""The live accuracy observatory: shadow-oracle auditing (ADR-016).

The sketch backend is approximate by design; this example shows the
observatory measuring HOW approximate, live. A deliberately undersized
sketch serves Zipf traffic through the real asyncio door while the
auditor mirrors a hash-coherent sample of decisions into an exact
shadow oracle off the hot path — then prints the live false-deny rate
with its Wilson confidence interval, the per-slice attribution, the
top-K consumer analytics off the heavy-hitter side table, and the
admission-SLO burn-rate block. Run on any host:

    JAX_PLATFORMS=cpu python examples/14_accuracy_observatory.py

The served form (everything below is also one curl against a real
server — gate it like every debug surface, docs/OPERATIONS.md §6):

    python -m ratelimiter_tpu.serving --backend mesh --audit \
        --audit-sample 64 --audit-token s3cret --hh-slots 256 \
        --http-port 8433
    curl -H 'Authorization: Bearer s3cret' \
        http://localhost:8433/debug/audit | jq
"""

import os

if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax

jax.config.update("jax_enable_x64", True)

import asyncio
import json

import numpy as np

from ratelimiter_tpu import (
    Algorithm,
    Config,
    ManualClock,
    SketchParams,
    create_limiter,
)
from ratelimiter_tpu.evaluation import zipf_key_ids
from ratelimiter_tpu.observability import MetricsDecorator, Registry, audit
from ratelimiter_tpu.observability.slo import SloBurnTracker
from ratelimiter_tpu.serving import AsyncClient, RateLimitServer

T0 = 1_700_000_000.0

# A geometry small enough that collisions actually bite (width 256 for
# ~6K active keys), so the observatory has something to see. The hh
# side table tracks hot keys exactly — that is what the top-K consumer
# analytics export.
cfg = Config(
    algorithm=Algorithm.SLIDING_WINDOW, limit=50, window=60.0,
    max_batch_admission_iters=1, key_prefix="",
    sketch=SketchParams(depth=2, width=256, sub_windows=60,
                        conservative_update=True, hh_slots=64,
                        hh_promote_fraction=0.2))

reg = Registry()


async def main() -> None:
    clock = ManualClock(T0)
    lim = MetricsDecorator(
        create_limiter(cfg, backend="sketch", clock=clock), reg)
    server = RateLimitServer(lim, max_batch=2048, max_delay=100e-6,
                             registry=reg)
    await server.start()

    # The observatory: OFF by default (the doors' tap is one None
    # check — byte-identical hot path). enable() installs the
    # process-wide auditor; sample=8 audits 1/8 of the keyspace so this
    # short run collects a meaningful sample (production default: 64).
    auditor = audit.enable(cfg, sample=8, registry=reg)
    slo = SloBurnTracker(reg, objective=0.999, latency_target=0.025)
    slo.attach()

    client = await AsyncClient.connect(server.host, server.port)
    ids = zipf_key_ids(n_keys=3000, n_requests=12_000, alpha=1.1, seed=0)
    for start in range(0, 12_000, 2048):
        end = min(start + 2048, 12_000)
        clock.set(T0 + start / 20_000.0)   # 20K req/s of virtual time
        await client.allow_hashed(ids[start:end].astype(np.uint64))
    await client.close()
    await server.shutdown()

    assert auditor.flush(timeout=30), "audit queue did not drain"
    st = auditor.status()
    lo, hi = st["false_deny_wilson95"]
    print("== live accuracy (shadow oracle, hash-coherent 1/8 sample) ==")
    print(f"  audited decisions : {st['samples']}"
          f"  (dropped: {st['dropped_decisions']})")
    print(f"  false-deny rate   : {st['false_deny_rate']:.5f}"
          f"  95% Wilson [{lo:.5f}, {hi:.5f}]")
    print(f"  false-allow rate  : {st['false_allow_rate']:.2e}")
    print(f"  fail-open samples : {st['fail_open_samples']}")

    print("== top consumers (hh side table — hash tokens, never keys) ==")
    base = lim.inner  # the undecorated sketch
    for row in base.consumer_stats(k=5)["top"]:
        print(f"  {row['consumer']}  in_window={row['in_window']}"
              f"  share={row['share']:.3f}")

    print("== admission SLO burn rate ==")
    print(json.dumps(slo.status()["windows"], indent=2))

    print("== the same families on /metrics ==")
    for line in reg.render().splitlines():
        if line.startswith(("rate_limiter_audit_false_deny_rate",
                            "rate_limiter_audit_samples",
                            "rate_limiter_top_consumer_mass",
                            "rate_limiter_slo_burn_rate")):
            print(" ", line)

    slo.detach()
    audit.disable()
    lim.close()
    print("OK")


if __name__ == "__main__":
    asyncio.run(main())
