"""Hierarchical cascades + adaptive control (ADR-020).

Three things in one runnable, in-process tour:

1. **the cascade** — with ``HierarchySpec`` every decision evaluates
   key → tenant → global scopes in ONE device dispatch (tenant ids
   derive on device from the key→tenant map; nothing tenant-shaped is
   ever on the wire), with all-or-nothing admission;
2. **weighted fair sharing** — under global contention, tenants split
   the contended mass proportionally to their weights, on device;
3. **the AIMD controller** — a hot-tenant storm saturates the global
   scope, the controller tightens the attacker's EFFECTIVE limit
   (floor-bounded, ceiling untouched), and after the storm clears it
   additively recovers back to the ceiling.

    JAX_PLATFORMS=cpu python examples/17_multitenant.py

Serving form: ``--tenants/--tenant/--assign/--controller`` on the
server binary, live management over bearer-gated ``/v1/tenants``.
Runbook: docs/OPERATIONS.md §11; decisions: docs/ADR/020.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "1")  # device backends need x64

import numpy as np  # noqa: E402

from ratelimiter_tpu import (  # noqa: E402
    Algorithm,
    Config,
    HierarchySpec,
    ManualClock,
    create_limiter,
)
from ratelimiter_tpu.core.config import SketchParams  # noqa: E402
from ratelimiter_tpu.hierarchy import AIMDController, AIMDGains  # noqa: E402

T0 = 1_700_000_000.0
WINDOW = 60.0


def cascade_basics():
    print("== 1. the cascade: key -> tenant -> global, one dispatch ==")
    cfg = Config(
        algorithm=Algorithm.SLIDING_WINDOW, limit=4, window=WINDOW,
        sketch=SketchParams(depth=2, width=1 << 12, sub_windows=4),
        hierarchy=HierarchySpec(tenants=4, global_limit=50))
    lim = create_limiter(cfg, backend="sketch", clock=ManualClock(T0))
    lim.set_tenant("gold", 6, weight=3)
    for k in ("g1", "g2", "g3"):
        lim.assign_tenant(k, "gold")

    # Per-key limit is 4, but gold's TENANT scope caps its three keys
    # at 6 per window combined: 12 attempts admit only 6.
    got = sum(int(lim.allow(k).allowed)
              for k in ("g1", "g2", "g3") * 4)
    print(f"  gold demand 12 (3 keys x 4 under per-key limit 4) "
          f"-> admitted {got} (tenant ceiling 6)")
    # Unassigned keys ride the default tenant -- gold's cap never
    # touches them.
    print(f"  unassigned key: allowed={lim.allow('other').allowed} "
          f"(default tenant, not gold)")
    st = lim.hierarchy_stats()
    print(f"  in-window mass: gold={st['tenants']['gold']['in_window']} "
          f"global={st['global']['in_window']}")
    lim.close()


def fair_sharing():
    print("== 2. weighted fair sharing under global contention ==")
    weights = {"small": 1, "mid": 2, "big": 5}
    cfg = Config(
        algorithm=Algorithm.SLIDING_WINDOW, limit=1000, window=WINDOW,
        sketch=SketchParams(depth=2, width=1 << 12, sub_windows=4),
        hierarchy=HierarchySpec(tenants=4, global_limit=96))
    lim = create_limiter(cfg, backend="sketch", clock=ManualClock(T0))
    rng = np.random.default_rng(5)
    keys = []
    for name, w in weights.items():
        lim.set_tenant(name, 10_000, weight=w)
        for i in range(16):
            lim.assign_tenant(f"{name}_k{i}", name)
            keys.extend([f"{name}_k{i}"] * 4)
    rng.shuffle(keys)

    # Every key bursts at once (a thundering herd): demand 192 against
    # global 96. The contended mass splits ~ 1:2:5, on device.
    out = lim.allow_batch(keys)
    got = np.asarray(out.allowed, dtype=bool)
    per = {name: int(sum(ok for k, ok in zip(keys, got)
                         if k.startswith(name))) for name in weights}
    print(f"  demand {len(keys)} vs global 96 -> admitted {int(got.sum())}")
    for name, w in weights.items():
        print(f"    {name:6s} weight {w}: admitted {per[name]}")
    lim.close()


def adaptive_control():
    print("== 3. AIMD: tighten under a hot-tenant storm, recover after ==")
    cfg = Config(
        algorithm=Algorithm.SLIDING_WINDOW, limit=100_000, window=WINDOW,
        sketch=SketchParams(depth=2, width=1 << 12, sub_windows=4),
        hierarchy=HierarchySpec(tenants=4, global_limit=1200))
    clock = ManualClock(T0)
    lim = create_limiter(cfg, backend="sketch", clock=clock)
    lim.set_tenant("attacker", 1000, weight=1, floor=50)
    lim.set_tenant("victim", 1000, weight=6, floor=50)
    atk = [f"atk{i}" for i in range(40)]
    vic = [f"vic{i}" for i in range(8)]
    for k in atk:
        lim.assign_tenant(k, "attacker")
    for k in vic:
        lim.assign_tenant(k, "victim")
    # In-process tick driving (a server runs this on a background
    # thread via --controller); gains as in the bench.
    ctl = AIMDController(
        lim, interval=999.0,
        gains=AIMDGains(decrease_factor=0.7, increase_fraction=0.2,
                        cooldown_s=0.0))

    rng = np.random.default_rng(7)

    def frames(n, size, atk_frac):
        for _ in range(n):
            n_atk = int(size * atk_frac)
            keys = ([atk[int(i)] for i in
                     rng.integers(0, len(atk), size=n_atk)]
                    + [vic[int(i)] for i in
                       rng.integers(0, len(vic), size=size - n_atk)])
            rng.shuffle(keys)
            yield keys

    tick = 0.0
    for phase, n, size, frac in (("baseline", 6, 160, 0.3),
                                 ("storm", 6, 640, 0.9),
                                 ("recovery", 6, 160, 0.3)):
        clock.advance(2.5 * WINDOW)       # window rolls between phases
        lim.allow("phase-warmup")
        timeline = []
        for keys in frames(n, size, frac):
            lim.allow_batch(keys)
            ctl.tick(tick)                # off the decision path
            tick += 1.0
            timeline.append(lim.effective_limits()["attacker"])
        print(f"  {phase:9s} attacker effective limit per frame: "
              f"{timeline}")
    print(f"  controller moves: tightened={ctl.tightened} "
          f"relaxed={ctl.relaxed} (ceiling 1000, floor 50)")
    lim.close()


if __name__ == "__main__":
    cascade_basics()
    fair_sharing()
    adaptive_control()
    print("OK")
