"""The collective mesh router: one all_to_all dispatch per frame (ADR-024).

`MeshSpec.router="collective"` replaces the host's per-frame route work
(argsort by owner, per-slice sub-launches, scatter-back — ADR-013) with
ONE jitted shard_map dispatch: each device takes an even 1/n shard of
the frame, computes owners on device (`h64 % n`), routes rows to their
owning slice with `jax.lax.all_to_all`, runs the fused kernels on owned
rows, and routes results back to source order. Decisions are
bit-identical to the host router; the host's only per-frame route cost
is padding the frame to the shard shape (33x less host work measured —
MULTICHIP_r08.json `route_phase_us`). Run with a virtual mesh anywhere:

    XLA_FLAGS=--xla_force_host_platform_device_count=4 JAX_PLATFORMS=cpu \
        python examples/20_collective_router.py

The served form (refused with --quarantine: one mesh-wide dispatch has
whole-mesh blast radius, so per-slice failure domains cannot hold):

    python -m ratelimiter_tpu.serving --backend mesh --mesh-devices 8 \
        --router collective --native --max-batch 16384
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

if os.environ.get("JAX_PLATFORMS"):
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax

jax.config.update("jax_enable_x64", True)

if len(jax.devices()) < 4:
    print("SKIP: need >= 4 devices (see module docstring)")
    raise SystemExit(0)

import numpy as np

from ratelimiter_tpu import Algorithm, Config, ManualClock, SketchParams
from ratelimiter_tpu.core.config import MeshSpec
from ratelimiter_tpu.core.errors import InvalidConfigError
from ratelimiter_tpu import create_limiter

T0 = 1.7e9


def cfg(router):
    return Config(algorithm=Algorithm.SLIDING_WINDOW, limit=5, window=60.0,
                  sketch=SketchParams(depth=2, width=1024, sub_windows=6),
                  mesh=MeshSpec(devices=4, router=router))


# The same mixed frames (keys spanning all 4 slices, a hot id recurring
# in-frame) through both routers: the all_to_all path must route every
# row to the same owner AND hand results back in frame order, so the
# hot id's sixth occurrence is denied at the same row either way.
host = create_limiter(cfg("host"), backend="mesh", clock=ManualClock(T0))
coll = create_limiter(cfg("collective"), backend="mesh",
                      clock=ManualClock(T0))

rng = np.random.default_rng(0)
for i in range(3):
    ids = rng.integers(1, 1 << 40, size=96, dtype=np.uint64)
    ids[::16] = np.uint64(0xBEEF)
    rh = host.allow_ids(ids, now=T0 + i * 0.5)
    rc = coll.allow_ids(ids, now=T0 + i * 0.5)
    np.testing.assert_array_equal(rh.allowed, rc.allowed)
    np.testing.assert_array_equal(rh.remaining, rc.remaining)
print("mixed frames: collective bit-identical to the host router")
print("router stats:", coll.router_stats())
assert coll.router_stats()["fallbacks"] == 0

# Skew beyond the bin headroom is never dropped: the device step
# commits nothing, the frame falls back to the host router exactly
# once, and the fallback is counted. (headroom < 1 forces capacity-1
# bins so a 4-copy frame must overflow.)
tight = create_limiter(
    Config(algorithm=Algorithm.SLIDING_WINDOW, limit=5, window=60.0,
           sketch=SketchParams(depth=2, width=1024, sub_windows=6),
           mesh=MeshSpec(devices=4, router="collective",
                         bin_headroom=0.001)),
    backend="mesh", clock=ManualClock(T0))
hot = np.full(4, 0xF00D, dtype=np.uint64)
r = tight.allow_ids(hot, now=T0)
assert r.allowed.tolist() == [True] * 4
assert tight.router_stats()["fallbacks"] >= 1
print("overflow fallback: admission exact, fallbacks counted")
tight.close()

# Quarantine is refused loudly — one mesh-wide dispatch cannot honor
# per-slice failure domains (ADR-015 vs ADR-024).
try:
    create_limiter(
        Config(algorithm=Algorithm.SLIDING_WINDOW, limit=5, window=60.0,
               sketch=SketchParams(depth=2, width=1024),
               mesh=MeshSpec(devices=4, router="collective",
                             quarantine=True)),
        backend="mesh", clock=ManualClock(T0))
    raise AssertionError("collective+quarantine must be refused")
except InvalidConfigError as exc:
    print("quarantine refused:", str(exc)[:60], "...")

coll.close()
host.close()
print("OK")
