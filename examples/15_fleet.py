"""The fleet tier: many hosts, one limiter (ADR-017).

Spins up a TWO-member fleet as real server subprocesses, then shows the
three behaviors that make N processes one limiter:

1. affine routing — FleetClient partitions every frame by keyspace
   owner and fans out (zero forwarding, the fast path);
2. mis-routed traffic — a "dumb LB" sends everything to one member,
   whose forwarder proxies foreign rows to their owner (answers stay
   bit-identical, one key's quota counts once fleet-wide);
3. per-range failover — kill -9 one member and its successor adopts
   the range (restored from the dead member's snapshot + WAL suffix),
   bumping the ownership epoch; the client self-heals off the new map.

    JAX_PLATFORMS=cpu python examples/15_fleet.py

Production shape: docs/OPERATIONS.md §9 and deployments/fleet-compose.yml.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def spawn(port, cfgpath, self_id, snap):
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + env.get("PYTHONPATH", "").split(os.pathsep))
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.Popen(
        [sys.executable, "-m", "ratelimiter_tpu.serving",
         "--backend", "sketch", "--limit", "100", "--window", "600",
         "--sketch-width", "8192", "--sub-windows", "6",
         "--port", str(port), "--no-prewarm",
         "--snapshot-dir", snap, "--snapshot-interval", "500",
         "--fleet-config", cfgpath, "--fleet-self", self_id,
         "--fleet-forward-deadline", "60",
         "--fleet-heartbeat", "0.3", "--fleet-dead-after", "1.5"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)


def wait_banner(proc):
    while True:
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError("fleet member died at start")
        if line.startswith("serving"):
            return


def main() -> None:
    from ratelimiter_tpu.serving.client import Client, FleetClient

    tmp = tempfile.mkdtemp(prefix="rl-fleet-demo-")
    pa, pb = free_port(), free_port()
    fleet = {"buckets": 32, "epoch": 1, "hosts": [
        {"id": "a", "host": "127.0.0.1", "port": pa,
         "ranges": [[0, 16]], "successor": "b",
         "snapshot_dir": os.path.join(tmp, "a")},
        {"id": "b", "host": "127.0.0.1", "port": pb,
         "ranges": [[16, 32]], "successor": "a",
         "snapshot_dir": os.path.join(tmp, "b")}]}
    cfgpath = os.path.join(tmp, "fleet.json")
    with open(cfgpath, "w", encoding="utf-8") as f:
        json.dump(fleet, f, indent=1)
    a = spawn(pa, cfgpath, "a", os.path.join(tmp, "a"))
    b = spawn(pb, cfgpath, "b", os.path.join(tmp, "b"))
    try:
        wait_banner(a)
        wait_banner(b)
        print(f"fleet up: a:{pa} owns buckets [0,16), "
              f"b:{pb} owns [16,32)")

        # ---- 1. affine routing: the fleet client partitions by owner.
        fc = FleetClient(fleet)
        res = fc.allow_batch([f"user:{i}" for i in range(100)])
        print(f"affine: {sum(r.allowed for r in res)}/100 allowed "
              f"across both members")

        # ---- 2. dumb LB: everything lands on a; foreign rows forward.
        with Client(port=pa, timeout=120) as ca:
            res = ca.allow_batch([f"user:{i}" for i in range(100)])
            print(f"mis-routed via a: {sum(r.allowed for r in res)}/100 "
                  f"(b's rows proxied, same answers)")
            # One key, both entry points, ONE quota.
            owner = int(fc.map.owner_of_hash(fc._hash(["hot"]))[0])
            used = sum(ca.allow_n("hot", 10).allowed for _ in range(12))
            print(f"'hot' (owner {fleet['hosts'][owner]['id']}): "
                  f"{used}x10 allowed of limit 100 through the "
                  f"non-owner door too")

        # ---- 3. failover: consume + snapshot on a, then kill -9.
        ka = next(f"k:{i}" for i in range(99)
                  if int(fc.map.owner_of_hash(fc._hash([f"k:{i}"]))[0])
                  == 0)
        with Client(port=pa, timeout=120) as ca:
            ca.allow_n(ka, 30)
            ca.set_override("vip", 42)
            ca.snapshot()
        a.send_signal(signal.SIGKILL)
        a.wait(timeout=30)
        t0 = time.time()
        while time.time() - t0 < 90:
            try:
                fc.allow_n(ka, 1)
                break
            except Exception:
                time.sleep(0.2)
        print(f"failover: b adopted a's range in "
              f"{time.time() - t0:.1f}s (epoch {fc.map.epoch})")
        with Client(port=pb, timeout=120) as cb:
            print(f"override survived: vip -> {cb.get_override('vip')}")
        denied = not fc.allow_n(ka, 75).allowed
        print(f"counters survived: {ka} already ~31/100 consumed, "
              f"75 more denied={denied}")
        fc.close()
        print("OK")
    finally:
        for proc in (a, b):
            if proc.poll() is None:
                proc.terminate()
        for proc in (a, b):
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()


if __name__ == "__main__":
    main()
