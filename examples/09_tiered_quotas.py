"""Tiered quotas with the policy engine: free/pro/enterprise keys with
different limits, decided together in single fused batches.

The reference documents this pattern as "run one limiter per tier and
route keys yourself" (its docs/EXAMPLES.md tiered-quota section). Here
tiers are per-key overrides in a device-resident policy table, resolved
by a vectorized binary search INSIDE the decision step — one limiter,
one dispatch per batch, any mix of tiers.
"""

import jax

jax.config.update("jax_enable_x64", True)  # device backends need int64 state math

import numpy as np

from ratelimiter_tpu import Algorithm, Config, ManualClock, create_limiter

clock = ManualClock(1_700_000_000.0)
cfg = Config(algorithm=Algorithm.TPU_SKETCH, limit=5, window=60.0)  # free tier
lim = create_limiter(cfg, backend="sketch", clock=clock)

# -- tier table: overrides pin ABSOLUTE limits per key ----------------
lim.set_override("pro:alice", 20)
lim.set_override("ent:acme", 100)
print(f"overrides live: {lim.override_count()} "
      f"({[(k, ov.limit) for k, ov in lim.list_overrides()]})")

# -- one mixed batch, every key decided against its OWN limit ---------
batch = (["free:bob"] * 8      # free tier: 5 admitted
         + ["pro:alice"] * 25  # pro tier: 20 admitted
         + ["ent:acme"] * 40)  # enterprise: all 40 admitted (of 100)
out = lim.allow_batch(batch)
free = int(np.sum(out.allowed[:8]))
pro = int(np.sum(out.allowed[8:33]))
ent = int(np.sum(out.allowed[33:]))
print(f"free:bob {free}/8  pro:alice {pro}/25  ent:acme {ent}/40")
assert (free, pro, ent) == (5, 20, 40)

# Results carry the key's effective limit (X-RateLimit-Limit material).
assert out.results()[10].limit == 20

# -- downgrades apply immediately; deletes return to the default ------
lim.set_override("pro:alice", 10)   # already consumed 20 -> denied now
assert not lim.allow("pro:alice").allowed
lim.delete_override("ent:acme")
assert lim.get_override("ent:acme") is None

# Over a running server the same management surface is:
#   POST/GET/DELETE /v1/policy?key=K&limit=N  (HTTP, bearer-gated)
#   SetOverride / GetOverride / DeleteOverride (gRPC)
#   set_override / get_override / delete_override (binary protocol client)
lim.close()
print("OK")
