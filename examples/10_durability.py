"""Durability: write-ahead log + async snapshots + crash recovery.

The crash contract (docs/ADR/009): policy overrides and dynamic config
recover EXACTLY via WAL replay; decision counters recover to the newest
snapshot — the crash window under-counts, erring toward allowing.

Server-binary equivalent of everything below:

    python -m ratelimiter_tpu.serving --snapshot-dir /var/lib/ratelimiter
    curl -X POST http://HOST:PORT/v1/snapshot         # manual trigger
"""

import tempfile

from ratelimiter_tpu import (
    Algorithm,
    Config,
    ManualClock,
    PersistenceSpec,
    create_limiter,
)
from ratelimiter_tpu.persistence import PersistenceManager

T0 = 1_700_000_000.0

with tempfile.TemporaryDirectory() as state_dir:
    cfg = Config(
        algorithm=Algorithm.SLIDING_WINDOW, limit=10, window=60.0,
        persistence=PersistenceSpec(dir=state_dir,
                                    snapshot_interval=30.0))

    # Boot: manager owns the WAL + background snapshotter; the wrapper
    # (outermost decorator) routes every mutation through the log.
    mgr = PersistenceManager(cfg.persistence)
    lim = mgr.wrap(create_limiter(cfg, backend="exact",
                                  clock=ManualClock(T0)))
    mgr.attach([lim])
    mgr.recover()            # empty dir: no-op
    mgr.start()              # interval snapshots in the background

    assert lim.allow_n("user:alice", 4).allowed     # pre-snapshot history
    lim.set_override("vip", 50)                     # WAL record 1
    entry = mgr.snapshot_now()                      # manual trigger
    print(f"snapshot {entry['id']} at WAL watermark {entry['wal_seq']}")

    assert lim.allow_n("user:alice", 3).allowed     # crash window: lost
    lim.set_override("vip2", 99)                    # crash window: WAL-exact
    mgr.wal.close()          # simulate kill -9 (no graceful snapshot)

    # Restart on the same directory.
    mgr2 = PersistenceManager(cfg.persistence)
    lim2 = mgr2.wrap(create_limiter(cfg, backend="exact",
                                    clock=ManualClock(T0)))
    mgr2.attach([lim2])
    report = mgr2.recover()
    print(f"recovered: {report.summary()}")

    # Overrides: exact. Counters: the 4 pre-snapshot requests survived,
    # the 3 in the crash window are re-admittable (under-count only).
    assert lim2.get_override("vip").limit == 50
    assert lim2.get_override("vip2").limit == 99
    assert not lim2.allow_n("user:alice", 7).allowed   # >= 4 consumed
    assert lim2.allow_n("user:alice", 6).allowed       # <= 4 consumed

    mgr2.stop()              # graceful: takes a final snapshot
    lim2.close()
    lim.close()

print("OK")
