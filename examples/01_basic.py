"""Basic limiting: create, allow, deny, retry-after, reset.

Runs on the exact (host) backend — no device needed.
"""

from ratelimiter_tpu import Algorithm, Config, create_limiter

lim = create_limiter(
    Config(algorithm=Algorithm.SLIDING_WINDOW, limit=5, window=60.0),
    backend="exact")

for i in range(5):
    res = lim.allow("user:1")
    print(f"request {i}: allowed={res.allowed} remaining={res.remaining}")

res = lim.allow("user:1")
print(f"over limit: allowed={res.allowed} retry_after={res.retry_after:.1f}s")

lim.reset("user:1")
print(f"after reset: allowed={lim.allow('user:1').allowed}")
lim.close()
print("OK")
