# Dev loop for ratelimiter_tpu (reference Makefile:16-93 analog).
# All targets run against the repo in place; PYTHONPATH is appended, never
# replaced (the existing PYTHONPATH carries the TPU plugin registration).

PY ?= python
REPO := $(abspath $(dir $(lastword $(MAKEFILE_LIST))))
export PYTHONPATH := $(REPO):$(PYTHONPATH)

.PHONY: help test test-all test-serving test-mesh test-collective test-tracing test-chaos \
        test-audit test-fleet test-fleet-forward test-fleet-obs \
        test-reshard test-hierarchy test-leases test-placement test-shm \
        test-neteng lint check \
        native bench bench-quick bench-audit bench-chaos bench-fleet \
        bench-fleet-obs bench-reshard bench-hierarchy bench-leases \
        bench-rebalance bench-shm bench-neteng bench-matrix serve verify \
        clean

help:            ## list targets
	@grep -E '^[a-z-]+:.*##' $(MAKEFILE_LIST) | sed 's/:.*##/\t/'

test:            ## fast suite (CPU, 8 virtual devices; excludes slow gates)
	$(PY) -m pytest tests/ -q -m "not slow"

test-all:        ## full suite including slow accuracy/scale gates
	$(PY) -m pytest tests/ -q

test-serving:    ## serving tier only
	$(PY) -m pytest tests/test_serving.py -q

test-mesh:       ## mesh contract + multichip + slice-parallel serving tests
	$(PY) -m pytest tests/test_contract_mesh.py tests/test_multichip.py \
	    tests/test_mesh_serving.py tests/test_scatter_gather.py -q

test-collective: ## collective router parity + overflow fallback (ADR-024)
	$(PY) -m pytest tests/test_collective_router.py -q

test-tracing:    ## flight-recorder span trees, both doors (ADR-014)
	$(PY) -m pytest tests/test_tracing.py -q

test-chaos:      ## failure-domain chaos suite + client resilience (ADR-015)
	XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	    $(PY) -m pytest tests/test_chaos.py tests/test_client_resilience.py -q

test-audit:      ## live accuracy observatory (ADR-016): engine, taps, /debug/audit
	XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	    $(PY) -m pytest tests/test_audit.py -q

test-fleet:      ## fleet tier (ADR-017): map/routing/forwarding/failover, 2+ real server processes
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_fleet.py -q

test-fleet-forward: ## coalesced forward lanes (ADR-019): ordering oracle, window failure attribution, 4-host routing
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_fleet_forward.py -q

test-fleet-obs:  ## fleet control tower (ADR-021): trace stitching, mergeable rollup, event journal, metric-name drift gate (slow lane unfiltered)
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_fleet_obs.py \
	    tests/test_metrics_docs.py -q

test-reshard:    ## elastic lifecycle (ADR-018): re-bucketing oracle, migration/rejoin/departure, handoff chaos
	XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	    $(PY) -m pytest tests/test_reshard.py tests/test_elastic.py -q

test-hierarchy:  ## hierarchical cascades + AIMD (ADR-020): oracle pinning, fair share, controller, both doors, mesh
	XLA_FLAGS=--xla_force_host_platform_device_count=8 \
	    $(PY) -m pytest tests/test_hierarchy.py tests/test_hierarchy_serving.py -q

test-leases:     ## client-embedded quota leases (ADR-022): protocol, debit-upfront oracle, revocation chaos, kill -9, both doors, fleet
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_leases.py -q

test-placement:  ## load-aware placement (ADR-023): planner determinism, chaos rebalance oracle, journal spill, real-process operator flow (slow lane unfiltered)
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_placement.py -q

test-shm:        ## shared-memory wire lane (ADR-025): uds/shm both doors, bit-identical pins, kill -9, ring fuzz, revocation-over-shm
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_shm_transport.py -q

test-neteng:     ## multi-ring network engine (ADR-026): epoll==uring byte parity, asserted probe downgrade, mid-frame death, slow-loris, fairness, shm-over-uring
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_net_engine.py -q

bench-fleet:     ## fleet scale-out numbers (single vs 2/4-host affine/mixed sweep + failover JSON, ADR-019)
	JAX_PLATFORMS=cpu $(PY) bench.py --fleet-hosts 4

bench-fleet-obs: ## all-observability-on fleet retention (interleaved off/on pairs, OBS_r01 JSON, ADR-021)
	JAX_PLATFORMS=cpu $(PY) bench.py --fleet-obs

bench-reshard:   ## elastic lifecycle numbers (migration window / rolling-restart retention / rejoin JSON)
	JAX_PLATFORMS=cpu $(PY) bench.py --reshard

bench-audit:     ## live-vs-offline accuracy agreement + audit overhead A/B JSON
	$(PY) bench.py --audit

bench-chaos:     ## degraded-serving numbers (retention/entry/recovery JSON)
	$(PY) bench.py --chaos slow-slice

bench-hierarchy: ## cascade overhead ratio + abuse-scenario numbers (tighten/recover timeline JSON, ADR-020)
	JAX_PLATFORMS=cpu $(PY) bench.py --hierarchy

bench-leases:    ## client-embedded lease numbers (leased vs wire rate, storm bound, Wilson delta, LEASE_r01 JSON, ADR-022)
	JAX_PLATFORMS=cpu $(PY) bench.py --leases

bench-rebalance: ## load-aware placement numbers (skewed fleet convergence, moved-range oracle, off-pin, REBALANCE_r01 JSON, ADR-023)
	JAX_PLATFORMS=cpu $(PY) bench.py --rebalance

bench-shm:       ## transport ladder A/B (interleaved tcp/uds/shm paired rounds, wire-phase breakdown, SHM_r01 JSON, ADR-025)
	$(PY) bench.py --shm

bench-neteng:    ## network-engine conn sweep (baseline vs multi-ring paired rounds at 16..512 conns, syscalls/decision, NETENG_r01 JSON, ADR-026)
	JAX_PLATFORMS=cpu $(PY) bench.py --conn-sweep

lint:            ## in-repo linter (ruff config in pyproject.toml where available)
	$(PY) tools/lint.py

check: lint test ## what CI runs on every push

cpp-client:      ## build + conformance-test the native C++ client
	$(PY) -m pytest tests/test_cpp_client.py -q

native:          ## (re)build the C++ bulk hasher extension in place
	rm -f ratelimiter_tpu/native/_hasher.so
	$(PY) -c "from ratelimiter_tpu.native import native_available; \
	          assert native_available(), 'build failed (g++ required)'; \
	          print('native hasher built')"

bench:           ## headline benchmark, one JSON line (real chip if present)
	$(PY) bench.py

bench-quick:     ## 3-second smoke bench
	BENCH_SECONDS=3 $(PY) bench.py

bench-matrix:    ## full matrix + BASELINE configs + e2e serving bench
	$(PY) -m benchmarks

serve:           ## run the server binary locally (exact backend, instant start)
	$(PY) -m ratelimiter_tpu.serving --backend exact --algorithm fixed_window \
	    --limit 100 --window 60 --port 8432

verify:          ## driver protocol: entry() compile + 8-device mesh dry run
	XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
	    $(PY) __graft_entry__.py

clean:           ## remove caches and build artifacts
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null || true
	rm -f ratelimiter_tpu/native/_hasher.so ratelimiter_tpu/native/_hasher_r*.so
	rm -f ratelimiter_tpu/native/_server.so ratelimiter_tpu/native/_server_r*.so
	rm -rf .pytest_cache
